"""Benchmark: honest batched-interpreter throughput + the
time-to-convergence corpus A/B.

Emission is HEADLINE-FIRST and incremental: the complete one-line JSON
record prints as soon as the headline phases (static prune,
transitions, ONE convergence pair) finish — inside
`MYTHRIL_BENCH_HEADLINE_S` (default 8 min) — and prints again after
every refinement (second pair, default path, hard solve). The LAST
parseable line is the record; a capture window that closes mid-refine
still holds a complete artifact (the round-5 rc:124/parsed:null fix,
hardened).

The record carries three measurement groups:

1. `state_transitions_per_sec` (the `value` field): one state
   transition = one EVM instruction applied to one path state — the
   unit of work of the reference's `execute_state` hot loop
   (mythril/laser/ethereum/svm.py:303). A single jit'd step advances
   every lane of a StateBatch at once on the TPU.

   Honesty rules (round-2): timing stops only after a forced
   device->host readback, and the measurement must scale ~linearly
   with step count (a dispatch-only "measurement" would not).

2. The **convergence corpus A/B** (round-5 headline). The round-4
   equal-budget design measured the timeout, not the engine (both
   legs' walls pinned at budget x contracts; issues tied —
   BASELINE.md round-4 reconciliation). This one measures WALL TO
   FIXPOINT: `CONV_CONTRACTS` contracts (analysis/corpusgen.py
   `synth_bench_corpus` — fixture constant-mutants plus deep-loop and
   cap-degrading shapes) analyzed at `-t 2` under a budget high
   enough that both legs CONVERGE, so a faster engine finishes
   sooner instead of exploring more states inside the same wall.

   Device leg: the round-5 inversion — one striped device exploration
   owns every contract it covers end-to-end (issues synthesized from
   banked concrete evidence, host walk skipped;
   --device-ownership/analysis/corpus.py), the host walking only the
   remainder with witness injection + solve pre-emption. Host leg:
   the same analyzer, chip off. Interleaved x `CONV_PAIRS`, medians,
   spread-gated. Explicit `criteria` fields state the round's
   pass/fail thresholds so the record cannot blur: speedup
   (host_wall/device_wall) >= 2.0 with distinct-finding parity.

3. The default single-contract path with its prepass/solver counters.

Baseline: the reference cannot run in this image (z3 is absent — its
entire solving surface is z3, mythril/laser/smt/solver/solver.py), and
it publishes no numbers (BASELINE.md). The normative proxy, recorded
in BASELINE.md, is therefore this repo's own host-only leg — the same
analyzer with the accelerator disabled. `vs_baseline` is the measured
median host-only wall over the median device wall on the convergence
A/B: the speedup the chip delivers over the proxy, not a constant.
"""

from __future__ import annotations

import json
import os
import signal
import statistics
import sys
import time

#: Internal wall-clock budget for the WHOLE bench process (both
#: attempts share it), sized below the harness's 870 s capture window:
#: the bench must ALWAYS emit its one parseable JSON line with whatever
#: phases completed, instead of being killed by the outer `timeout`
#: (BENCH_r05.json's rc:124/parsed:null failure mode). Phases that
#: don't fit the remaining budget are skipped and say so in the record.
BENCH_BUDGET_S = float(os.environ.get("MYTHRIL_BENCH_BUDGET_S", "780"))
_BENCH_T0 = time.monotonic()

#: The HEADLINE deadline: the record must be printed (complete, with
#: transitions + one convergence pair) by this wall mark even when the
#: full budget would allow more — the capture window must never close
#: on a bench that has measured everything but printed nothing
#: (BENCH_r05's rc:124/parsed:null failure mode). Later phases REFINE
#: the record and print it again; the last line supersedes.
HEADLINE_DEADLINE_S = float(
    os.environ.get("MYTHRIL_BENCH_HEADLINE_S", "480")
)


def _budget_left() -> float:
    return BENCH_BUDGET_S - (time.monotonic() - _BENCH_T0)


def _headline_left() -> float:
    return HEADLINE_DEADLINE_S - (time.monotonic() - _BENCH_T0)


# sizes are env-tunable so the tier-1 smoke (tests/test_bench_smoke.py)
# can drive the REAL emission path at toy scale
N_LANES = int(os.environ.get("MYTHRIL_BENCH_LANES", "16384"))
N_STEPS = int(os.environ.get("MYTHRIL_BENCH_STEPS", "256"))
CONV_CONTRACTS = int(os.environ.get("MYTHRIL_BENCH_CONTRACTS", "32"))
CONV_PAIRS = int(os.environ.get("MYTHRIL_BENCH_PAIRS", "2"))
#: per-contract ceiling, NOT the expected wall: contracts converge
#: (walk reaches fixpoint) well under it; the ceiling only bounds
#: pathological mutants
CONV_EXEC_TIMEOUT_S = 90
#: the device leg's exploration allowance — this IS the chip carrying
#: the workload, so it is sized for coverage, not minimized
CONV_DEVICE_BUDGET_S = 180.0
SPREAD_GATE = 0.35
#: covers the configured worst case (every contract at the ceiling +
#: the device exploration allowance) — the deadline guards HANGS, it
#: must not fire on a merely pathological corpus
LEG_DEADLINE_S = CONV_CONTRACTS * CONV_EXEC_TIMEOUT_S + 600
SPEEDUP_TARGET = 2.0


def _timed_run(batch, code, max_steps: int) -> float:
    """Run the batched interpreter and return wall seconds measured
    through a forced host readback (the only sync this platform
    honors)."""
    import numpy as np

    from mythril_tpu.laser.batch.run import run

    t0 = time.perf_counter()
    out, steps = run(batch, code, max_steps=max_steps)
    # np.asarray forces device execution AND the device->host copy;
    # summing both fields makes the readback depend on the full result.
    sync = int(np.asarray(out.pc).sum())
    n_live = int((np.asarray(out.status) == 0).sum())
    dt = time.perf_counter() - t0
    assert sync >= 0  # keep the readback live
    assert int(steps) == max_steps, f"early halt at {int(steps)}/{max_steps}"
    # the demo contract loops forever; a dead lane means transitions
    # would overcount masked no-op work
    assert n_live == out.pc.shape[0], f"lanes died: {n_live}/{out.pc.shape[0]}"
    return dt


#: v5e(lite) headline numbers for the roofline denominators
HBM_BYTES_PER_S = 819e9
PEAK_BF16_FLOPS = 197e12


def _roofline(batch, code, rate: float) -> dict:
    """Bytes-per-step / roofline accounting for the step kernel.

    The batched interpreter is integer vector work — the MXU (the
    FLOPs headline) is idle by design, so MFU is ~0 and the honest
    utilization axis is HBM: a functional step reads and writes the
    whole StateBatch (XLA fuses/elides some of it, so this is an upper
    bound on demanded traffic) plus the two code-table gathers. The
    interesting diagnosis is which side of the roofline the measured
    rate lands on: demanded-bytes x steps/s far under the HBM ceiling
    means the kernel is DISPATCH/latency-bound, not bandwidth-bound —
    macro-stepping (unroll) is then the lever, not layout."""
    state_bytes = sum(
        getattr(a, "nbytes", 0) for a in batch
    )
    gather_bytes = N_LANES * (33 + 6 * 4)  # code window + opcode metadata
    bytes_per_step = 2 * state_bytes + gather_bytes
    steps_per_sec = rate / N_LANES
    demanded = bytes_per_step * steps_per_sec
    return {
        "state_bytes_per_lane": int(state_bytes // N_LANES),
        "bytes_per_step": int(bytes_per_step),
        "batch_steps_per_sec": round(steps_per_sec, 2),
        "hbm_demand_gbps": round(demanded / 1e9, 2),
        "hbm_utilization_pct": round(100 * demanded / HBM_BYTES_PER_S, 2),
        "mfu_pct": 0.0,  # integer kernel: no MXU FLOPs by design
        "roofline_bound": (
            "bandwidth" if demanded > 0.5 * HBM_BYTES_PER_S else "dispatch"
        ),
    }


def bench_transitions() -> dict:
    import jax

    from __graft_entry__ import _demo_workload

    batch, code = _demo_workload(N_LANES)

    # Warmup at both step counts so neither timed call includes compile.
    _timed_run(batch, code, N_STEPS)
    _timed_run(batch, code, N_STEPS // 4)

    dt_full = _timed_run(batch, code, N_STEPS)
    dt_quarter = _timed_run(batch, code, N_STEPS // 4)

    # Linearity gate: 4x the steps must cost >=2x the wall time (slack
    # for fixed dispatch/readback overhead). A lazy "finish" fails this.
    # The upper bound catches the opposite failure: a transient tunnel
    # stall during the full run (observed once: ratio 19.4, recorded
    # rate understated 5x) — raise so the __main__ retry reruns clean.
    ratio = dt_full / max(dt_quarter, 1e-9)
    if ratio < 2.0:
        raise RuntimeError(
            f"non-linear scaling (t({N_STEPS})={dt_full:.3f}s vs "
            f"t({N_STEPS // 4})={dt_quarter:.3f}s, ratio {ratio:.2f}) — "
            "the timer is not observing execution"
        )
    if ratio > 8.0:
        raise RuntimeError(
            f"full run stalled (ratio {ratio:.2f} for 4x steps) — "
            "transient device/link interference; retrying gives an "
            "honest number instead of an understated one"
        )

    transitions = N_LANES * N_STEPS
    rate = transitions / dt_full
    print(
        f"bench: {transitions} transitions in {dt_full:.3f}s "
        f"(quarter-run {dt_quarter:.3f}s, ratio {ratio:.2f}) on "
        f"{jax.devices()[0]}",
        file=sys.stderr,
    )
    out = {"rate": rate, "wall_s": dt_full, "scaling_ratio": ratio}
    out.update(_roofline(batch, code, rate))
    return out


def bench_specialize_ab(dev: dict) -> dict:
    """Generic-vs-specialized step-throughput A/B (ISSUE 6): the SAME
    demo workload timed on the generic interpreter (the transitions
    half above, `dev["rate"]`) and on its contract-specialized kernel
    (laser/batch/specialize.py: phase pruning + superblock fusion).
    The specialized leg's transition count includes the instructions
    the fused substeps advanced — both legs count executed EVM
    instructions per second."""
    import numpy as np

    import jax.numpy as jnp

    from __graft_entry__ import _demo_workload
    from mythril_tpu.laser.batch import specialize as spec_mod

    batch, code = _demo_workload(N_LANES)
    length = int(np.asarray(code.length)[0])
    raw = bytes(np.asarray(code.ops)[0, :length].tolist())
    # the production kernel-selection path: pruning from the signature,
    # fusion only where the superblock profile profits
    phases = spec_mod.phases_for(
        spec_mod.signature_for(raw), fuse=spec_mod.fuse_profitable(raw)
    )
    fuse = jnp.asarray(
        spec_mod.build_fuse_table([raw], code.ops.shape[1] - 33)
    )
    kern = spec_mod.kernel_cache().get(phases)

    def timed(max_steps: int):
        t0 = time.perf_counter()
        out, steps, fused, _blocks = kern.run(
            batch, code, fuse, max_steps=max_steps
        )
        sync = int(np.asarray(out.pc).sum())  # forced readback
        n_fused = int(fused)
        dt = time.perf_counter() - t0
        assert sync >= 0
        return dt, int(steps), n_fused

    timed(N_STEPS)  # warmup: the one specialized-kernel compile
    dt, steps, n_fused = timed(N_STEPS)
    assert steps == N_STEPS, f"early halt at {steps}/{N_STEPS}"
    # the demo contract loops forever, so every lane executes every
    # full step; the fused substeps add on top
    transitions = N_LANES * steps + n_fused
    spec_rate = transitions / dt
    out = {
        "specialized_step_rate": round(spec_rate, 1),
        "specialized_wall_s": round(dt, 3),
        "specialized_fused_steps": n_fused,
        "spec_pruned_phases": len(phases.pruned),
    }
    if dev.get("rate"):
        out["generic_step_rate"] = round(dev["rate"], 1)
        out["specialize_speedup"] = round(spec_rate / dev["rate"], 3)
    print(f"bench: specialize A/B {out}", file=sys.stderr)
    return out


#: the blockjit A/B's own lane count: the SPEEDUP is a ratio of two
#: legs at the same shape, so it does not need the headline's 16k
#: lanes — a smaller shape keeps both compiles + runs inside the leg
#: deadline on a 1-core host
BJ_LANES = int(os.environ.get("MYTHRIL_BENCH_BJ_LANES", "2048"))


def _blockjit_workload(n_lanes: int):
    """The block-JIT A/B workload: an arithmetic/compare/bitwise loop
    body — the straight-line chains PR-6 fusion cannot advance (every
    ALU op breaks a PUSH/DUP/SWAP run) but block lowering can. One
    CFG block per pass: JUMPDEST; (MUL, ADD, XOR, DUP/EQ/POP mix);
    JUMP — the dominant compiled-Solidity shape for hashing/math-heavy
    function bodies."""
    import numpy as np

    from mythril_tpu.laser.batch.state import make_batch, make_code_table

    body = bytes([
        0x60, 0x01,        # PUSH1 1 (seed)
        0x5B,              # 2: JUMPDEST  — loop head
        0x60, 0x03, 0x02,  # PUSH1 3; MUL
        0x60, 0x07, 0x01,  # PUSH1 7; ADD
        0x60, 0x55, 0x18,  # PUSH1 0x55; XOR
        0x80, 0x60, 0x2A,  # DUP1; PUSH1 42
        0x10, 0x50,        # LT; POP
        0x80, 0x19, 0x16,  # DUP1; NOT; AND
        0x60, 0x02, 0x56,  # PUSH1 2; JUMP
    ])
    code = make_code_table([body])
    rng = np.random.default_rng(1)
    calldata = [rng.integers(0, 256, 36, dtype=np.uint8).tobytes()
                for _ in range(n_lanes)]
    batch = make_batch(n_lanes, calldata=calldata)
    return batch, code, body


def bench_blockjit_ab() -> dict:
    """Specialized-vs-blockjit step-throughput A/B (ISSUE 13): the
    SAME ALU-dense workload timed on the PR-6 specialized kernel
    (phase pruning + superblock fusion, block_depth=0) and on the
    block-JIT kernel (whole lowered CFG blocks per iteration). Both
    legs count executed EVM instructions per second (full steps x
    lanes + substep-advanced instructions), so the speedup is the
    honest blocks-vs-stack-shuffles ratio the acceptance gates on."""
    import numpy as np

    import jax.numpy as jnp

    from mythril_tpu.laser.batch import blockjit as bj_mod
    from mythril_tpu.laser.batch import ensure_compile_cache
    from mythril_tpu.laser.batch import specialize as spec_mod

    ensure_compile_cache()  # both legs' compiles persist across runs
    batch, code, raw = _blockjit_workload(BJ_LANES)
    cap = code.ops.shape[1] - 33
    signature = spec_mod.signature_for(raw)
    fuse_on = spec_mod.fuse_profitable(raw)
    spec_phases = spec_mod.phases_for(signature, fuse=fuse_on)
    depth = bj_mod.block_depth_for(raw)
    bj_phases = spec_mod.phases_for(
        signature, fuse=fuse_on, block_depth=depth
    )
    fuse_tbl = jnp.asarray(spec_mod.build_fuse_table([raw], cap))
    block_tbl = jnp.asarray(bj_mod.build_block_table([raw], cap))
    bstats = bj_mod.block_stats(raw)

    def timed(phases, tbl, max_steps: int):
        kern = spec_mod.kernel_cache().get(phases)
        t0 = time.perf_counter()
        out, steps, subs, blocks = kern.run(
            batch, code, tbl, max_steps=max_steps
        )
        sync = int(np.asarray(out.pc).sum())  # forced readback
        n_subs, n_blocks = int(subs), int(blocks)
        dt = time.perf_counter() - t0
        assert sync >= 0
        return dt, int(steps), n_subs, n_blocks

    # warmup both compiles, then time
    timed(spec_phases, fuse_tbl, N_STEPS)
    timed(bj_phases, block_tbl, N_STEPS)
    s_dt, s_steps, s_subs, _ = timed(spec_phases, fuse_tbl, N_STEPS)
    b_dt, b_steps, b_subs, b_blocks = timed(bj_phases, block_tbl, N_STEPS)
    spec_rate = (BJ_LANES * s_steps + s_subs) / s_dt
    bj_rate = (BJ_LANES * b_steps + b_subs) / b_dt
    out = {
        "blockjit_step_rate": round(bj_rate, 1),
        "blockjit_wall_s": round(b_dt, 3),
        "blockjit_substep_steps": b_subs,
        "blockjit_block_rate": round(b_blocks / b_dt, 1),
        "blockjit_speedup": round(bj_rate / spec_rate, 3),
        "blockjit_depth": depth,
        "blockjit_fallback_blocks": bstats["blocks_unlowered"],
        "blockjit_lowered_density": bstats["lowered_density"],
        "spec_leg_step_rate": round(spec_rate, 1),
    }
    print(f"bench: blockjit A/B {out}", file=sys.stderr)
    return out


def bench_static_prune() -> dict:
    """The static layer (analysis/static) over the benchmark corpus:
    pure host work, no device — measures what fraction of the corpus's
    statically-decidable units (branch directions, dispatcher
    selectors, basic blocks) the pre-dispatch pass proves dead, i.e.
    lanes/flips/modules the arena never wastes. Runs first: it is
    milliseconds and must not be skippable by budget exhaustion."""
    from mythril_tpu.analysis.corpusgen import synth_bench_corpus
    from mythril_tpu.analysis.static import summary_for

    contracts = synth_bench_corpus(CONV_CONTRACTS)
    t0 = time.perf_counter()
    pruned = total = dead_selectors = dead_directions = 0
    mounted_semantic = mounted_opcode = registered = 0
    static_answerable = 0
    taint_wall_ms = 0.0
    for code, _creation, _name in contracts:
        summary = summary_for(code)
        pruned += summary.prune_units
        total += summary.total_units
        dead_selectors += len(summary.dead_selectors)
        dead_directions += len(summary.prune_directions())
        # the semantic-vs-opcode screen A/B (the strictly-reduces
        # acceptance reads both rates) + the triage-tier population
        sem_app, sem_skip = summary.applicable_modules()
        opc_app, _opc_skip = summary.applicable_modules(semantic=False)
        mounted_semantic += len(sem_app)
        mounted_opcode += len(opc_app)
        registered += len(sem_app) + len(sem_skip)
        static_answerable += bool(summary.static_answerable)
        if summary.taint is not None:
            taint_wall_ms += summary.taint.wall_ms
    return {
        "static_prune_rate": round(pruned / total, 4) if total else 0.0,
        "static_dead_selectors": dead_selectors,
        "static_dead_directions": dead_directions,
        "screen_mount_rate_opcode": (
            round(mounted_opcode / registered, 4) if registered else 0.0
        ),
        "screen_mount_rate_semantic": (
            round(mounted_semantic / registered, 4) if registered else 0.0
        ),
        "static_answer_rate": (
            round(static_answerable / len(contracts), 4)
            if contracts
            else 0.0
        ),
        "static_taint_wall_s": round(taint_wall_ms / 1e3, 3),
        "static_wall_s": round(time.perf_counter() - t0, 3),
        **bench_static_link(contracts),
    }


def bench_static_link(contracts) -> dict:
    """The cross-contract linker leg (analysis/static/linkset): link
    the bench corpus plus the known-positive fixture families and
    report resolution quality. Headline fields:

    - `link_resolve_rate`: resolved / total call-site edges (the
      planted fixtures all resolve, organic corpus edges may not);
    - `proxy_detect_rate`: detected proxies / planted proxies (2x
      EIP-1967 + 2x EIP-1167 here — must be 1.0);
    - `callgraph_fingerprint_hit_rate`: selectors that got a linked
      fingerprint / all selectors (the rest carry link-unresolved /
      link-cycle problems and can never serve a linked store hit);
    - `static_link_wall_s`: the whole corpus-level link pass (the
      admission-path budget: sub-second).
    """
    from mythril_tpu.analysis.corpusgen import (
        cross_call_pair,
        minimal_proxy,
        proxy_pair,
    )
    from mythril_tpu.analysis.static import link_corpus

    planted_proxies = 4
    rows = list(contracts)
    for k in range(2):
        rows.extend(proxy_pair(seed=k, collide=bool(k % 2)))
        rows.extend(minimal_proxy(seed=k))
    rows.extend(cross_call_pair(seed=0))
    t0 = time.perf_counter()
    linkset = link_corpus(rows)
    stats = linkset.stats()
    data = linkset.resolve()
    fps = sum(len(v) for v in data["linked_fingerprints"].values())
    problems = sum(len(v) for v in data["link_problems"].values())
    return {
        "link_resolve_rate": stats["resolve_rate"],
        "proxy_detect_rate": (
            round(stats["proxies"] / planted_proxies, 4)
        ),
        "callgraph_fingerprint_hit_rate": (
            round(fps / (fps + problems), 4) if fps + problems else 1.0
        ),
        "link_proxy_pairs": stats["proxy_pairs"],
        "link_collisions": stats["collisions"],
        "static_link_wall_s": round(time.perf_counter() - t0, 3),
    }


class _Deadline(Exception):
    pass


def _with_deadline(fn, seconds: int):
    """Run fn() under a SIGALRM deadline; raises _Deadline."""

    def _alarm(signum, frame):
        raise _Deadline()

    previous = signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(seconds)
    try:
        return fn()
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, previous)


def _corpus_leg(contracts, use_device, deadline_s=None):
    """One A/B leg. Legs share one process, so the query memo is
    cleared each time — without the reset the second leg would ride
    the first leg's solves.

    `deadline_s` bounds the leg INSIDE the analyzer (the supervisor is
    consulted at every contract boundary, emitting a partial result
    list) — the BENCH_r05 fix: the SIGALRM wrapper alone can be
    swallowed by per-contract error containment, which let a host leg
    run 691s past its alarm and the outer timeout kill the process
    with no JSON emitted (rc:124/parsed:null)."""
    from mythril_tpu import observe
    from mythril_tpu.analysis.corpus import analyze_corpus
    from mythril_tpu.support.model import clear_cache
    from mythril_tpu.laser.smt.solver.solver_statistics import (
        SolverStatistics,
    )

    stats = SolverStatistics()
    stats.enabled = True
    clear_cache()
    d0 = stats.device_sat_count
    solver_marker = observe.solver_marker()
    t0 = time.perf_counter()
    results = analyze_corpus(
        contracts,
        transaction_count=2,
        execution_timeout=CONV_EXEC_TIMEOUT_S,
        create_timeout=10,
        use_device=use_device,
        device_budget_s=CONV_DEVICE_BUDGET_S if use_device is None else None,
        processes=1,
        deadline_s=deadline_s,
        on_timeout="partial",
        # multi-chip: with >1 visible device the device leg runs the
        # mesh corpus scheduler (one wave engine per device group,
        # work stealing) — the single-chip leg is unchanged
        devices=_bench_devices() if use_device is None else None,
    )
    wall = time.perf_counter() - t0
    prepass = max(
        ((r.get("device_prepass") or {}) for r in results),
        key=lambda s: s.get("device_steps", 0),
    )
    # distinct findings: the criteria metric. The reference re-reports
    # some classes per end-state (ExternalCalls dedupe=False), so raw
    # counts measure duplication, not coverage.
    distinct = len(
        {
            (r["name"], i["swc-id"], i["address"])
            for r in results
            for i in r["issues"]
        }
    )
    # span-derived device overlap for THIS leg: only wave.device spans
    # that closed after the leg started count
    leg_spans = [
        s
        for s in observe.flight_recorder().tail(8192)
        if s.t1 >= t0
    ]
    return {
        "wall_s": round(wall, 1),
        "issues": sum(len(r["issues"]) for r in results),
        "distinct_issues": distinct,
        "states": sum(r.get("states", 0) for r in results),
        "errors": sum(1 for r in results if r["error"]),
        "owned": sum(1 for r in results if r.get("owned")),
        "device_sat": stats.device_sat_count - d0,
        "solver_attribution": observe.solver_attribution(solver_marker),
        "trace_overlap_frac": observe.overlap_fraction(
            leg_spans, name="wave.device"
        ),
        "prepass": prepass or None,
    }


def _spread(values) -> float:
    med = statistics.median(values)
    return (max(values) - min(values)) / med if med else 0.0


def _bench_devices():
    """Device-group count for the mesh scheduler: the visible device
    count when there is more than one chip, else None (single
    engine)."""
    try:
        import jax

        n = len(jax.devices())
        return n if n > 1 else None
    except Exception:
        return None


class _ConvAB:
    """Incremental device/host time-to-convergence A/B: pairs
    accumulate one at a time and summarize() re-aggregates after every
    pair, so main() can print a COMPLETE record after the first pair
    (headline-first) and refine it while budget remains."""

    def __init__(self):
        from mythril_tpu.analysis.corpusgen import synth_bench_corpus

        self.contracts = synth_bench_corpus(CONV_CONTRACTS)
        self.device_legs = []
        self.host_legs = []

    def _leg_deadline(self, cap=None) -> int:
        # each leg promises only the wall the bench budget still holds
        # (minus slack for the later bench halves); a leg that cannot
        # fit raises _Deadline NOW so the record says "deadline"
        # instead of the outer timeout killing the process mid-leg
        room = _budget_left() - 90
        if cap is not None:
            room = min(room, cap)
        room = int(min(LEG_DEADLINE_S, room))
        if room < 30:
            raise _Deadline()
        return room

    def warmup(self) -> None:
        """Warm the wave kernels at the legs' exact shapes (one
        untimed wave): jit tracing + compile are once-per-machine
        costs (persistent compile cache), not per-corpus costs, and
        the first device leg must not carry them into the median."""
        try:
            from mythril_tpu.analysis.corpus import corpus_device_prepass

            # budget 0: each phase still opens its one unconditional
            # wave, through the SAME sizing rules (lanes/caps/mesh)
            # the timed legs resolve
            _with_deadline(
                lambda: corpus_device_prepass(
                    self.contracts,
                    budget_s=0.0,
                    mesh_groups=_bench_devices(),
                ),
                min(240, self._leg_deadline()),
            )
            print("bench: corpus wave kernels warmed", file=sys.stderr)
        except _Deadline:
            raise
        except Exception as e:
            print(f"bench: corpus warmup skipped: {e!r}", file=sys.stderr)

    def run_pair(self, headline: bool = False) -> None:
        """One interleaved device+host pair. A headline pair is
        additionally bounded by the headline window so the FIRST
        record prints inside the capture window no matter what the
        corpus does."""
        import logging

        logging.disable(logging.WARNING)
        try:
            for use_device, bucket in (
                (None, self.device_legs),
                (False, self.host_legs),
            ):
                cap = None
                if headline:
                    legs_left = 2 if use_device is None else 1
                    cap = max(30, int((_headline_left() - 30) / legs_left))
                room = self._leg_deadline(cap)
                bucket.append(
                    _with_deadline(
                        lambda room=room, ud=use_device: _corpus_leg(
                            self.contracts, ud,
                            deadline_s=max(30, room - 30),
                        ),
                        room,
                    )
                )
        finally:
            logging.disable(logging.NOTSET)
        pair = len(self.host_legs)
        print(
            f"bench: conv pair {pair}/{CONV_PAIRS}: device "
            f"{self.device_legs[-1]['wall_s']}s/"
            f"{self.device_legs[-1]['distinct_issues']} findings "
            f"({self.device_legs[-1]['owned']} owned) vs host "
            f"{self.host_legs[-1]['wall_s']}s/"
            f"{self.host_legs[-1]['distinct_issues']} findings",
            file=sys.stderr,
        )

    def summarize(self, strict: bool = True) -> dict:
        """Aggregate whatever pairs have run: medians + spreads +
        explicit criteria (the same record shape at every refinement).
        With `strict` and >1 pair, a spread-gate violation raises so
        __main__'s retry reruns the measurement."""
        device_legs, host_legs = self.device_legs, self.host_legs
        if not device_legs or not host_legs:
            return {}
        d_walls = [leg["wall_s"] for leg in device_legs]
        h_walls = [leg["wall_s"] for leg in host_legs]
        d_spread, h_spread = _spread(d_walls), _spread(h_walls)
        spread_rejected = (
            len(d_walls) > 1 and max(d_spread, h_spread) > SPREAD_GATE
        )
        if spread_rejected and strict:
            raise RuntimeError(
                f"convergence A/B spread gate: device {d_spread:.2f} / "
                f"host {h_spread:.2f} exceeds {SPREAD_GATE} — the regime "
                "is too noisy to record"
            )

        # the prepass counters of the median device leg (the recorded one)
        median_leg = device_legs[
            d_walls.index(sorted(d_walls)[len(d_walls) // 2])
        ]
        d_wall = statistics.median(d_walls)
        h_wall = statistics.median(h_walls)
        d_found = int(
            statistics.median([leg["distinct_issues"] for leg in device_legs])
        )
        h_found = int(
            statistics.median([leg["distinct_issues"] for leg in host_legs])
        )
        speedup = round(h_wall / d_wall, 3) if d_wall else None
        out = {
            "corpus_contracts": len(self.contracts),
            "spread_rejected": spread_rejected,
            "corpus_pairs": len(host_legs),
            "corpus_exec_timeout_s": CONV_EXEC_TIMEOUT_S,
            "corpus_wall_s": d_wall,
            "corpus_wall_spread": round(d_spread, 3),
            "corpus_issues": d_found,
            "corpus_issues_raw": int(
                statistics.median([leg["issues"] for leg in device_legs])
            ),
            "corpus_owned_contracts": int(
                statistics.median([leg["owned"] for leg in device_legs])
            ),
            "corpus_errors": max(leg["errors"] for leg in device_legs),
            "host_only_wall_s": h_wall,
            "host_only_wall_spread": round(h_spread, 3),
            "host_only_issues": h_found,
            "host_only_issues_raw": int(
                statistics.median([leg["issues"] for leg in host_legs])
            ),
            "contracts_per_sec": round(len(self.contracts) / d_wall, 3)
            if d_wall
            else None,
            "device_sat_verdicts_corpus": sum(
                leg["device_sat"] for leg in device_legs
            ),
            "corpus_walls_device": d_walls,
            "corpus_walls_host": h_walls,
            # the round's pass/fail thresholds, stated in the artifact so
            # narrative and record cannot diverge (round-4 lesson)
            "criteria": {
                "speedup_def": "median host_only_wall_s / corpus_wall_s",
                "speedup_target": SPEEDUP_TARGET,
                "speedup_measured": speedup,
                "speedup_pass": bool(
                    speedup is not None and speedup >= SPEEDUP_TARGET
                ),
                "findings_def": "median distinct (contract, swc, address)",
                "findings_device": d_found,
                "findings_host": h_found,
                "findings_parity_pass": d_found >= h_found,
            },
        }
        # per-origin solver attribution + span-derived wave overlap of
        # the recorded (median) device leg — the ISSUE-7 observability
        # fields (ROADMAP item 1 reads solver_attribution to see which
        # engine owns the verdicts)
        out["solver_attribution"] = median_leg.get(
            "solver_attribution"
        ) or {}
        out["trace_overlap_frac"] = median_leg.get(
            "trace_overlap_frac", 0.0
        )
        prepass = median_leg.get("prepass") or {}
        for k, v in prepass.items():
            if k not in ("scope", "partial", "mesh"):
                out[f"prepass_{k}"] = v
        # the pipelined-wave-engine headline metrics, promoted out of the
        # prepass_* namespace (ISSUE 4 acceptance: bench reports them)
        for alias in (
            "wave_overlap_ratio",
            "device_idle_frac",
            "evidence_bytes_per_wave",
            "waves_overlapped",
            "pipelined",
        ):
            if f"prepass_{alias}" in out:
                out[alias] = out[f"prepass_{alias}"]
        if out.get("prepass_evidence_bytes_full") and out.get(
            "prepass_evidence_bytes"
        ):
            out["evidence_compaction_ratio"] = round(
                out["prepass_evidence_bytes_full"]
                / max(1, out["prepass_evidence_bytes"]),
                2,
            )
        # mesh scheduler observability (ISSUE 5 acceptance: the bench
        # reports mesh_devices / steal_count / per-device occupancy)
        mesh = prepass.get("mesh") or {}
        out["mesh_devices"] = prepass.get(
            "mesh_devices", mesh.get("devices", 1)
        )
        out["mesh_groups"] = prepass.get("mesh_groups", mesh.get("groups", 1))
        out["steal_count"] = prepass.get("steal_count", mesh.get("steals", 0))
        out["rebalance_bytes"] = prepass.get(
            "rebalance_bytes", mesh.get("rebalance_bytes", 0)
        )
        out["mesh_occupancy"] = [
            {
                "group": g.get("group"),
                "occupancy": g.get("occupancy"),
                "waves": g.get("waves"),
                "steals": g.get("steals", 0),
            }
            for g in mesh.get("per_device", [])
        ]
        return out

def bench_hard_solve(budget_s: int = 300) -> dict:
    """The solver-race half (VERDICT r4 item 3): BEC-guard-shaped
    queries — `x*y/y != x (y != 0)`, the SWC-101 multiplication+
    division circuit — posed through the public Solver surface twice:

    - host leg: device solving OFF (pure incremental CDCL);
    - race leg: device solving ON — the CDCL marathon races the
      on-chip portfolio (laser/smt/solver/device_race.py), first
      answer wins, witnesses validated/extended before being believed.

    Each leg gets a fresh blast session (reset_blast_session) so the
    comparison is cold-for-cold. Reports per-leg walls plus the race
    scorecard (device_sat_verdicts_hard, race_wins/race_losses) —
    the counters the round-4 verdict asked to see in the artifact."""
    import random

    from mythril_tpu.support.support_args import args as _args
    from mythril_tpu.laser.smt import terms
    from mythril_tpu.laser.smt.solver.solver import (
        check_terms,
        reset_blast_session,
    )
    from mythril_tpu.laser.smt.solver.solver_statistics import (
        SolverStatistics,
    )

    rng = random.Random(41)
    W = 256

    def queries():
        out = []
        for k in range(3):
            x = terms.bv_var(f"hs_x{k}", W)
            y = terms.bv_var(f"hs_y{k}", W)
            q = terms.udiv(terms.mul(x, y), y)
            out.append(
                [
                    terms.bnot(terms.eq(q, x)),
                    terms.bnot(terms.eq(y, terms.bv_const(0, W))),
                    terms.ult(
                        terms.bv_const(rng.getrandbits(64), W), x
                    ),
                ]
            )
        return out

    stats = SolverStatistics()
    stats.enabled = True
    legs = {}
    restore = _args.device_solving
    # one materialization: both legs must solve the SAME instances
    # (terms are interned process-wide and survive the session reset)
    qs = queries()
    try:
        for leg, mode in (("host", "never"), ("race", "always")):
            _args.device_solving = mode
            reset_blast_session()
            d0, w0, l0 = (
                stats.device_sat_count, stats.race_wins, stats.race_losses,
            )
            walls = []
            sats = 0
            for cs in qs:

                def one(cs=cs):
                    t0 = time.perf_counter()
                    verdict, _model = check_terms(cs, timeout_ms=30_000)
                    return verdict, time.perf_counter() - t0

                try:
                    verdict, dt = _with_deadline(one, budget_s)
                except _Deadline:
                    verdict, dt = "deadline", float(budget_s)
                walls.append(round(dt, 1))
                sats += verdict == "sat"
            legs[leg] = {
                "walls": walls,
                "wall_s": round(sum(walls), 1),
                "sat": sats,
                "device_sat": stats.device_sat_count - d0,
                "race_wins": stats.race_wins - w0,
                "race_losses": stats.race_losses - l0,
            }
            print(f"bench: hard-solve {leg} leg {legs[leg]}", file=sys.stderr)
    finally:
        _args.device_solving = restore
        reset_blast_session()
    out = {
        "hard_solve_host_wall_s": legs["host"]["wall_s"],
        "hard_solve_race_wall_s": legs["race"]["wall_s"],
        "hard_solve_host_walls": legs["host"]["walls"],
        "hard_solve_race_walls": legs["race"]["walls"],
        "hard_solve_host_sat": legs["host"]["sat"],
        "hard_solve_race_sat": legs["race"]["sat"],
        "device_sat_verdicts_hard": legs["race"]["device_sat"],
        "race_wins": legs["race"]["race_wins"],
        "race_losses": legs["race"]["race_losses"],
    }
    if legs["race"]["wall_s"]:
        out["hard_solve_speedup"] = round(
            legs["host"]["wall_s"] / legs["race"]["wall_s"], 3
        )
    return out


def bench_device_default_path(budget_s: int = 210) -> dict:
    """The default `myth analyze` path with the device engaged: one
    reference contract analyzed single-process, reporting how much
    stepping/solving the TPU did. Runs last, under a deadline: the
    device kernels' first-compile cost must never sink the earlier
    metrics."""
    from mythril_tpu.analysis.goldens import GOLDEN_FIXTURES

    target = GOLDEN_FIXTURES / "exceptions.sol.o"
    if not target.exists():
        return {}

    import logging

    logging.disable(logging.WARNING)
    try:
        from mythril_tpu.analysis.corpus import analyze_corpus
        from mythril_tpu.laser.smt.solver.solver_statistics import (
            SolverStatistics,
        )

        stats = SolverStatistics()
        stats.enabled = True
        d0, c0 = stats.device_sat_count, stats.cdcl_sat_count
        t0 = time.perf_counter()

        def run():
            return analyze_corpus(
                [(target.read_text().strip(), "", target.stem)],
                transaction_count=2,
                execution_timeout=30,
                create_timeout=10,
                processes=1,
            )

        results = _with_deadline(run, budget_s)
        out = {
            "default_path_wall_s": round(time.perf_counter() - t0, 1),
            "default_path_issues": len(results[0]["issues"]),
            "device_sat_verdicts": stats.device_sat_count - d0,
            "cdcl_sat_verdicts": stats.cdcl_sat_count - c0,
        }
        for k, v in (results[0].get("device_prepass") or {}).items():
            out[f"default_prepass_{k}"] = v
    except _Deadline:
        print("bench: default-path half hit its deadline", file=sys.stderr)
        return {"default_path": "deadline"}
    except Exception as e:
        print(f"bench: default-path half skipped: {e!r}", file=sys.stderr)
        return {"default_path": "skipped"}
    finally:
        logging.disable(logging.NOTSET)
    print(f"bench: default path {out}", file=sys.stderr)
    return out


def bench_store(budget_s: int = 150) -> dict:
    """The duplicate-heavy verdict-store leg (mythril_tpu/store): a
    COLD corpus of base contracts analyzes host-only with write-back,
    then a WARM corpus of exact duplicates plus one-selector forks
    runs against the same store directory. At real traffic most
    submissions are the warm shape — the leg measures what the store
    refunds: `store_hit_rate` (exact settles / warm corpus),
    `incremental_rate` (fingerprint-diff re-analyses), and
    `warm_hit_p50_s` (median settle wall of an exact hit — the
    admission-tier latency a repeat job pays instead of a full
    pipeline)."""
    import statistics
    import tempfile

    from mythril_tpu.analysis.corpus import analyze_corpus
    from mythril_tpu.analysis.corpusgen import (
        deadweight_contract,
        fork_contract,
    )

    store_dir = tempfile.mkdtemp(prefix="myth-bench-store-")
    bases = [
        (fork_contract(0, 0), "", "storebase#0"),
        (fork_contract(1, 0), "", "storebase#1"),
        (deadweight_contract(0), "", "storebase#2"),
    ]
    leg_deadline = max(30.0, budget_s * 0.45)
    t0 = time.monotonic()
    analyze_corpus(
        bases,
        execution_timeout=8,
        processes=1,
        use_device=False,
        store_dir=store_dir,
        deadline_s=leg_deadline,
    )
    cold_wall = time.monotonic() - t0
    # warm traffic: every base resubmitted byte-for-byte, plus a fork
    # of base#0 whose SECOND function is untouched (one-selector
    # mutation — the incremental tier's population)
    warm_corpus = [
        (code, "", f"{name}#dupe") for code, _c, name in bases
    ] + [(fork_contract(0, 1), "", "storefork#0")]
    t1 = time.monotonic()
    warm = analyze_corpus(
        warm_corpus,
        execution_timeout=8,
        processes=1,
        use_device=False,
        store_dir=store_dir,
        deadline_s=leg_deadline,
    )
    warm_wall = time.monotonic() - t1
    hits = [r for r in warm if r and r.get("store_hit")]
    incrementals = [
        r for r in warm if r and r.get("store_incremental")
    ]
    out = {
        "store_hit_rate": round(len(hits) / len(warm_corpus), 3),
        "incremental_rate": round(
            len(incrementals) / len(warm_corpus), 3
        ),
        "warm_hit_p50_s": (
            round(
                statistics.median(
                    [r.get("wall_s") or 0.0 for r in hits]
                ),
                6,
            )
            if hits
            else None
        ),
        "store_cold_wall_s": round(cold_wall, 3),
        "store_warm_wall_s": round(warm_wall, 3),
    }
    print(f"bench: store leg {out}", file=sys.stderr)
    return out


def bench_journal(rounds: int = 48) -> dict:
    """Durable-journal (WAL) overhead on the warm admission tier
    (ISSUE 14): an engine-less service settling static-answer
    submissions — the fastest settle path the service has, so the
    per-record WAL cost shows up at its worst — with the journal on
    vs off. `journal_overhead_frac` = (p50_on - p50_off) / p50_off
    over that path; instant-tier settle records are written unsynced
    by design, so this measures the buffered-write cost (the fsync'd
    full-path figure is gated in tools/chaos_smoke.py against the
    warm wave p50). `journal_admit_p50_s` is the durable (fsync'd)
    admission record alone — the latency a queued submission pays for
    the crash-safety guarantee."""
    import statistics
    import tempfile

    from mythril_tpu.analysis.corpusgen import clean_contract
    from mythril_tpu.service.engine import AnalysisEngine, ServiceConfig
    from mythril_tpu.service.jobs import Job

    def leg(journal_dir):
        engine = AnalysisEngine(ServiceConfig(
            stripes=2, lanes_per_stripe=2, host_walk=False,
            queue_capacity=rounds * 2 + 8, journal_dir=journal_dir,
        ))
        walls = []
        for i in range(rounds):
            t0 = time.perf_counter()
            engine.submit(Job(clean_contract(i % 8)))
            walls.append(time.perf_counter() - t0)
        admits = []
        for _ in range(rounds // 2):
            t0 = time.perf_counter()
            engine.submit(Job("33ff"))  # queue path: fsync'd admit
            admits.append(time.perf_counter() - t0)
        # drop the first rounds (summary-cache warmup) per leg
        return (
            statistics.median(walls[8:]),
            statistics.median(admits),
        )

    p50_off, admit_off = leg(None)
    with tempfile.TemporaryDirectory(prefix="myth-bench-wal-") as jd:
        p50_on, admit_on = leg(jd)
    out = {
        "journal_overhead_frac": (
            round(max(0.0, (p50_on - p50_off)) / p50_off, 4)
            if p50_off
            else None
        ),
        "journal_warm_p50_off_s": round(p50_off, 6),
        "journal_warm_p50_on_s": round(p50_on, 6),
        "journal_admit_p50_s": round(admit_on, 6),
    }
    print(f"bench: journal leg {out}", file=sys.stderr)
    return out


def bench_fleet(jobs_per_leg: int = 6) -> dict:
    """Federated serving leg (ISSUE 15): in-process `myth serve`
    replicas behind a FleetFront.

    - `fleet_throughput_scale`: wall for the same full-wave job mix
      through a 1-replica front vs a 2-replica front (>= ~1 says the
      front stripes instead of serializing; true scaling needs real
      parallel hardware — on a 1-core CPU container the two engines
      time-slice, so the gate threshold is loose);
    - `fleet_failover_p50_s`: p50 of death-detection -> settle for
      jobs re-routed off a SIGKILLed-equivalent replica whose
      verdicts were already banked in the fleet-shared store — the
      reroute-after-restart-settles-in-microseconds claim, measured;
    - `fleet_reroute_dedup_rate`: deduped / rerouted on that leg.
    """
    import statistics
    import tempfile

    from mythril_tpu.fleet import FleetConfig, FleetFront
    from mythril_tpu.service.engine import ServiceConfig
    from mythril_tpu.service.server import AnalysisServer

    # module-applicable shapes (never static-answered: the jobs must
    # genuinely ride waves, or the legs only measure HTTP overhead)
    codes = [
        "33ff",  # selfdestruct(caller)
        "32ff",  # selfdestruct(origin)
        "336000556000ff",  # caller -> storage, then selfdestruct
    ]
    cfg = dict(
        stripes=2, lanes_per_stripe=4, steps_per_wave=128, max_waves=2,
        queue_capacity=32, host_walk=False, coalesce_wait_s=0.02,
        idle_wait_s=0.05,
    )
    fleet_kw = dict(
        probe_interval_s=0.2, failure_threshold=2, recovery_s=300.0
    )

    def throughput(n_replicas: int) -> float:
        servers = [
            AnalysisServer(ServiceConfig(**cfg)).start()
            for _ in range(n_replicas)
        ]
        front = FleetFront(
            FleetConfig([s.url for s in servers], **fleet_kw)
        ).start()
        try:
            # warm the wave kernel off the clock (shared compile cache
            # across replicas/legs: identical arena shape)
            warm = front.submit(codes[0], idempotency_key="fl-warm")
            front.report(warm.id, wait_s=240.0)
            t0 = time.perf_counter()
            batch = [
                front.submit(
                    codes[i % len(codes)],
                    idempotency_key=f"fl-tp{n_replicas}-{i}",
                )
                for i in range(jobs_per_leg)
            ]
            for job in batch:
                doc = front.report(job.id, wait_s=240.0)
                assert doc["state"] == "done", doc
            return time.perf_counter() - t0
        finally:
            front.close()
            for s in servers:
                s.close()

    t1 = throughput(1)
    t2 = throughput(2)

    # -- failover leg: banked verdicts re-route in microseconds -------
    # host_walk=True here: only a completed host walk writes its
    # verdict back to the shared store, and the banked verdict is what
    # the re-route dedupes through
    store_dir = tempfile.mkdtemp(prefix="myth-bench-fleet-")
    fo_cfg = dict(cfg, host_walk=True)
    victim = AnalysisServer(
        ServiceConfig(store_dir=store_dir, **fo_cfg)
    ).start()
    survivor = AnalysisServer(
        ServiceConfig(store_dir=store_dir, **fo_cfg)
    ).start()
    front = FleetFront(
        FleetConfig([victim.url, survivor.url], **fleet_kw)
    ).start()
    try:
        batch = []
        for i in range(jobs_per_leg):
            job = front.submit(
                codes[i % len(codes)], idempotency_key=f"fl-fo{i}"
            )
            batch.append(job)
        # wait until every job settled ON ITS REPLICA (polling the
        # replicas directly: the front still believes them in-flight,
        # which is exactly the crash window)
        server_of = {"r0": victim, "r1": survivor}
        deadline = time.monotonic() + 240.0
        for job in batch:
            client = server_of[job.replica].engine.queue
            while time.monotonic() < deadline:
                remote = client.get(job.remote_id)
                if remote is not None and remote.terminal:
                    break
                time.sleep(0.02)
        kill_t = time.monotonic()
        victim._httpd.shutdown()
        victim._httpd.server_close()
        while front.failovers == 0 and time.monotonic() - kill_t < 30:
            front.check_replicas()
        walls = []
        for job in batch:
            doc = front.report(job.id, wait_s=60.0)
            assert doc["state"] == "done", doc
            if job.rerouted and job.finished_t is not None and (
                job.failover_t is not None
            ):
                walls.append(job.finished_t - job.failover_t)
        fleet = front.stats()["fleet"]
        out = {
            "fleet_throughput_scale": (
                round(t1 / t2, 3) if t2 else None
            ),
            "fleet_throughput_1r_wall_s": round(t1, 3),
            "fleet_throughput_2r_wall_s": round(t2, 3),
            "fleet_failover_p50_s": (
                round(statistics.median(walls), 6) if walls else None
            ),
            "fleet_reroute_dedup_rate": (
                round(fleet["reroute_deduped"] / fleet["rerouted"], 3)
                if fleet["rerouted"]
                else None
            ),
            "fleet_rerouted_jobs": fleet["rerouted"],
        }
    finally:
        front.close()
        survivor.close()
        try:
            victim.engine._draining = True
            victim.engine._drained.set()
            victim.close()
        except Exception:
            pass
    print(f"bench: fleet leg {out}", file=sys.stderr)
    return out


def bench_chainstream(blocks: int = 30, per_block: int = 2) -> dict:
    """Chain-head streaming leg (ISSUE 16): a ChainWatcher over an
    in-process scripted chain (fake clients under the REAL
    RpcEndpoint/RpcPool/cursor/triage machinery; no network, no
    front — the fleet handoff is the fleet leg's problem).

    - `ingest_static_rate`: distinct contracts static-triaged per
      second on the ingest path (line-rate triage under a burst of
      `blocks * per_block` fresh deployments);
    - `alert_p50_s`: p50 block-seen -> alert-fired (gated: the SLO
      story wants it far under any real block time);
    - `head_lag_blocks_max`: deepest backlog observed while draining
      the burst with a bounded per-tick backfill batch;
    - `reorg_recovery_s`: wall for a 3-block reorg to resolve —
      rollback + retraction + canonical re-ingest to the new head.
    """
    import hashlib as _hashlib
    import statistics
    import tempfile

    from mythril_tpu.chainstream import ChainWatcher, RpcEndpoint, RpcPool
    from mythril_tpu.chainstream import WatchConfig
    from mythril_tpu.ethereum.interface.rpc.exceptions import (
        RpcErrorResponse,
    )

    def _sha(text):
        return "0x" + _hashlib.sha256(text.encode()).hexdigest()

    class _Chain:
        def __init__(self):
            self.blocks, self.codes, self.receipts = [], {}, {}
            self.add_block()

        def add_block(self, deployments=(), salt="main"):
            number = len(self.blocks)
            parent = (
                self.blocks[-1]["hash"] if self.blocks
                else "0x" + "0" * 64
            )
            txs = []
            for i, (address, code_hex) in enumerate(deployments):
                txh = _sha(f"tx:{number}:{i}:{salt}")
                txs.append({"hash": txh, "to": None, "input": "0x"})
                self.receipts[txh] = {"contractAddress": address}
                self.codes[address.lower()] = "0x" + code_hex
            self.blocks.append({
                "number": hex(number),
                "hash": _sha(f"block:{number}:{salt}"),
                "parentHash": parent,
                "transactions": txs,
            })

    class _Client:
        def __init__(self, chain):
            self.chain = chain

        def eth_blockNumber(self, timeout_s=None):
            return len(self.chain.blocks) - 1

        def eth_getBlockByNumber(self, block, tx_objects=True,
                                 timeout_s=None):
            number = block if isinstance(block, int) else int(block, 16)
            if 0 <= number < len(self.chain.blocks):
                return self.chain.blocks[number]
            raise RpcErrorResponse(-32001, "unknown block")

        def eth_getTransactionReceipt(self, tx_hash, timeout_s=None):
            return self.chain.receipts[tx_hash]

        def eth_getCode(self, address, default_block="latest",
                        timeout_s=None):
            return self.chain.codes.get(address.lower(), "0x")

    chain = _Chain()
    pool = RpcPool([RpcEndpoint("e0", _Client(chain), retries=0)])
    state = tempfile.mkdtemp(prefix="myth-bench-stream-")
    watcher = ChainWatcher(
        pool, state,
        config=WatchConfig(start_block=0, backfill_batch=8),
    )
    watcher.tick()  # genesis + static-layer warmup off the clock

    # -- ingest burst: every deployment a DISTINCT bytecode ------------
    n_contracts = 0
    for b in range(blocks):
        deployments = []
        for j in range(per_block):
            i = b * per_block + j
            # PUSH1 i PUSH1 0 SSTORE CALLER SELFDESTRUCT — distinct
            # code hash per contract, module-applicable (survivor)
            code = f"60{i % 256:02x}60005533ff"
            deployments.append((_sha(f"bench-dep:{i}")[:42], code))
            n_contracts += 1
        chain.add_block(deployments=deployments)
    lag_max = 0
    t0 = time.perf_counter()
    while watcher.head_lag() != 0 or watcher.head != len(chain.blocks) - 1:
        watcher.tick()
        lag_max = max(lag_max, watcher.head_lag() or 0)
    ingest_wall = time.perf_counter() - t0
    latencies = sorted(
        a.latency_s for a in watcher.alerts.alerts()
        if a.latency_s is not None
    )

    # -- 3-block reorg recovery ----------------------------------------
    chain.blocks = chain.blocks[:-3]
    for _ in range(4):  # the fork wins by one
        chain.add_block(salt="fork")
    t0 = time.perf_counter()
    while (
        watcher.cursor.tip() is None
        or watcher.cursor.tip().block_hash != chain.blocks[-1]["hash"]
    ):
        watcher.tick()
    reorg_wall = time.perf_counter() - t0
    watcher.close()
    out = {
        "ingest_static_rate": (
            round(n_contracts / ingest_wall, 1) if ingest_wall else None
        ),
        "alert_p50_s": (
            round(statistics.median(latencies), 6) if latencies else None
        ),
        "head_lag_blocks_max": lag_max,
        "reorg_recovery_s": round(reorg_wall, 6),
        "chainstream_reorgs": watcher.reorgs,
    }
    print(f"bench: chainstream leg {out}", file=sys.stderr)
    return out


def bench_compileplane() -> dict:
    """Zero-cold-start compile plane leg (ISSUE 17): bake a one-bucket
    kernel pack for a tiny dispatch shape, then measure both boot
    paths on the SAME arena avals —

    - `cold_ready_no_pack_s`: the in-process compile a packless
      replica pays before its first wave (the bake's own compile wall,
      which IS that compile);
    - `cold_ready_pack_s`: mount the pack + run the first wave off the
      deserialized AOT executable, zero in-process compiles;
    - `kernel_pack_hit_rate` (gated): pack hits over pack-consulting
      lookups — 1.0 on this leg, a drop means the load path broke;
    - `aot_load_p50_s`: p50 artifact deserialize wall.
    """
    import shutil
    import tempfile

    import jax

    from mythril_tpu.compileplane.pack import (
        _arena_for,
        bake_service_pack,
        service_shape,
    )
    from mythril_tpu.compileplane.plane import configure_plane, reset_plane
    from mythril_tpu.laser.batch.run import (
        clear_aot_generic,
        generic_aot_stats,
        wave_run,
    )

    shape_args = dict(
        stripes=2, lanes_per_stripe=2, steps_per_wave=32, code_cap=32
    )
    shape = service_shape(**shape_args)
    pack_dir = tempfile.mkdtemp(prefix="myth-bench-pack-")
    reset_plane()
    clear_aot_generic()
    try:
        manifest = bake_service_pack(pack_dir, [None], **shape_args)
        cold_no_pack = manifest["baked"][0]["wall_s"]

        # a "fresh replica": no plane, no AOT table, no jit caches
        reset_plane()
        clear_aot_generic()
        jax.clear_caches()
        plane = configure_plane(pack_dirs=(pack_dir,))
        t0 = time.perf_counter()
        mounted = plane.mount_packs()
        batch, table, _substep = _arena_for(shape)
        out_state = wave_run(
            batch,
            table,
            max_steps=shape["steps_per_wave"],
            track_coverage=True,
            donate=False,
        )
        jax.block_until_ready(out_state[1])
        cold_pack = time.perf_counter() - t0
        stats = plane.stats()
        out = {
            "cold_ready_no_pack_s": round(cold_no_pack, 3),
            "cold_ready_pack_s": round(cold_pack, 3),
            "compileplane_speedup": (
                round(cold_no_pack / cold_pack, 2) if cold_pack else None
            ),
            "kernel_pack_hit_rate": stats["kernel_pack_hit_rate"],
            "aot_load_p50_s": stats["aot_load_p50_s"],
            "compileplane_artifacts": manifest["artifacts"],
            "compileplane_mounted": mounted["mounted"],
            "compileplane_inproc_compiles": generic_aot_stats()["compiles"],
        }
    finally:
        reset_plane()
        clear_aot_generic()
        shutil.rmtree(pack_dir, ignore_errors=True)
    print(f"bench: compileplane leg {out}", file=sys.stderr)
    return out


def bench_router() -> dict:
    """Routed-vs-uniform tier-ladder A/B (ISSUE 19), the data
    flywheel demo in one leg:

    1. UNIFORM leg: today's ladder over a mixed synthetic corpus
       (every contract pays the device prepass), routing records
       accumulating in-process;
    2. train a router artifact FROM THAT LEG'S OWN records (`myth
       route train` under the hood) — the flywheel's first turn;
    3. ROUTED leg: the same corpus with the artifact mounted —
       cheap-predicted contracts skip straight to the host walk, the
       device budget concentrates on the rest, overruns promote.

    Headline fields: `routed_speedup` (gated — uniform wall over
    routed wall, must stay > 1), `routing_regret` (model-priced
    seconds the uniform leg burnt on mispriced routes),
    `router_artifact_version`."""
    import shutil
    import tempfile

    from mythril_tpu import observe, routing
    from mythril_tpu.analysis.corpus import analyze_corpus
    from mythril_tpu.analysis.corpusgen import synth_bench_corpus
    from mythril_tpu.support.model import clear_cache

    contracts = synth_bench_corpus(max(8, min(CONV_CONTRACTS, 16)))

    def _leg(router_dir=None, router_on=None):
        clear_cache()
        t0 = time.perf_counter()
        results = analyze_corpus(
            contracts,
            transaction_count=1,
            execution_timeout=8,
            create_timeout=10,
            use_device=True,  # the ladder under test, CPU backend or not
            processes=1,
            deadline_s=max(60, min(240, int(_budget_left() - 60))),
            on_timeout="partial",
            router_dir=router_dir,
            router=router_on,
        )
        return time.perf_counter() - t0, results

    log = observe.routing_log()
    log.clear()
    uniform_wall, _uniform = _leg(router_on=False)
    records = log.tail(4096)
    artifact_dir = tempfile.mkdtemp(prefix="myth-bench-router-")
    try:
        model = routing.train_model(records)  # ValueError when starved
        routing.save_router(artifact_dir, model)
        router = routing.load_router(artifact_dir)
        if router is None:
            raise RuntimeError("freshly saved router artifact refused")
        regret = None
        try:
            regret = routing.evaluate_log(records, router)["regret_s"]
        except Exception:
            pass
        log.clear()
        routed_wall, routed = _leg(router_dir=artifact_dir, router_on=True)
        out = {
            "router_uniform_wall_s": round(uniform_wall, 2),
            "router_routed_wall_s": round(routed_wall, 2),
            "routed_speedup": (
                round(uniform_wall / routed_wall, 3)
                if routed_wall else None
            ),
            "routing_regret": (
                round(regret, 3) if regret is not None else None
            ),
            "router_artifact_version": router.version,
            "router_trained_rows": model["trained_rows"],
            "router_routed_contracts": sum(
                1 for r in routed if r.get("routed")
            ),
            "router_promoted_contracts": sum(
                1 for r in routed if r.get("promoted")
            ),
        }
    finally:
        shutil.rmtree(artifact_dir, ignore_errors=True)
    print(f"bench: router leg {out}", file=sys.stderr)
    return out


def _emit(record: dict, stage: str) -> None:
    """Print the one-line JSON record NOW. Called after the headline
    phases (transitions + one convergence pair) and again after every
    refinement: a capture that closes at any point past the headline
    emit still holds a complete, parseable record — the last printed
    line supersedes earlier ones."""
    record["bench_emit"] = stage
    record["bench_wall_s"] = round(time.monotonic() - _BENCH_T0, 1)
    _device_saturation_fields(record)
    # tier circuit-breaker scorecard (ISSUE 14): cumulative trips
    # across every tier at emit time — a healthy run reports 0
    try:
        from mythril_tpu.support.breaker import trips_total

        record["breaker_trips"] = trips_total()
    except Exception:
        pass
    print(json.dumps(record), flush=True)


def _device_saturation_fields(record: dict) -> None:
    """The devicemon sample at emit time (ISSUE 12): how full the
    hardware was when this record closed — device memory where the
    backend reports it, process RSS everywhere, cumulative wave
    overlap/idle fractions."""
    try:
        from mythril_tpu import observe

        sample = observe.device_monitor().sample()
    except Exception as e:
        print(f"bench: device sample failed: {e!r}", file=sys.stderr)
        return
    record["device_host_rss_bytes"] = sample.get("host_rss_bytes")
    record["device_mem_bytes_in_use"] = sum(
        row.get("bytes_in_use") or 0
        for row in (sample.get("memory") or {}).values()
    ) or None
    record["device_wave_overlap_frac"] = sample.get("wave_overlap_frac")
    record["device_idle_frac"] = sample.get("idle_frac")


#: run-scoped markers for the solver flight-recorder fields: every
#: _emit reports the loss waterfall / capture count / cdcl-sat total
#: over the SAME window (main() start), so
#: sum(solver_loss_reasons.values()) == cdcl_sat_verdicts holds on
#: every printed record
_SOLVER_RUN_MARKER = None
_CDCL_SAT_BASE = 0
_DEVICE_SAT_BASE = 0


def _mark_solver_run() -> None:
    global _SOLVER_RUN_MARKER, _CDCL_SAT_BASE, _DEVICE_SAT_BASE
    from mythril_tpu import observe
    from mythril_tpu.laser.smt.solver.solver_statistics import (
        SolverStatistics,
    )

    _SOLVER_RUN_MARKER = observe.solver_marker()
    _CDCL_SAT_BASE = SolverStatistics().cdcl_sat_count
    _DEVICE_SAT_BASE = SolverStatistics().device_sat_count


def _solver_flight_fields(record: dict) -> None:
    """The flight-recorder scorecard (ISSUE 8): host-won loss reasons,
    captured-corpus size, and the matching run-scoped cdcl-sat count."""
    if _SOLVER_RUN_MARKER is None:
        return
    try:
        from mythril_tpu import observe
        from mythril_tpu.laser.smt.solver.solver_statistics import (
            SolverStatistics,
        )

        record["solver_loss_reasons"] = observe.loss_reasons(
            since=_SOLVER_RUN_MARKER, verdict="sat"
        )
        record["solver_loss_reasons_all"] = observe.loss_reasons(
            since=_SOLVER_RUN_MARKER
        )
        record["captured_queries"] = observe.captured_total(
            since=_SOLVER_RUN_MARKER
        )
        cdcl_sats = SolverStatistics().cdcl_sat_count - _CDCL_SAT_BASE
        device_sats = (
            SolverStatistics().device_sat_count - _DEVICE_SAT_BASE
        )
        record["cdcl_sat_verdicts"] = cdcl_sats
        record["device_sat_verdicts"] = device_sats
        # the ISSUE-9 acceptance headline: what fraction of this run's
        # SAT verdicts the accelerator OWNED (device-first funnel
        # target: > 0.5, up from 0.0 in BENCH_r02-r04)
        total_sats = cdcl_sats + device_sats
        record["device_verdict_share"] = (
            round(device_sats / total_sats, 3) if total_sats else 0.0
        )
    except Exception as e:
        print(f"bench: solver flight fields failed: {e!r}", file=sys.stderr)


def _refresh_headline(record: dict, dev: dict) -> None:
    """(Re)derive the cross-phase headline fields from the phase data
    currently in the record."""
    _solver_flight_fields(record)
    record["value"] = round(dev["rate"], 1) if "rate" in dev else None
    vs_baseline = None
    if record.get("corpus_wall_s") and record.get("host_only_wall_s"):
        vs_baseline = round(
            record["host_only_wall_s"] / record["corpus_wall_s"], 3
        )
    record["vs_baseline"] = vs_baseline
    # kernel-specialization scorecard: the process-wide compile-cache
    # counters at emit time (covers the A/B leg AND the corpus legs'
    # in-process explorers)
    try:
        from mythril_tpu.laser.batch.specialize import kernel_cache_stats

        ks = kernel_cache_stats()
        record["kernel_cache_hits"] = ks["hits"]
        record["kernel_cache_misses"] = ks["misses"]
        record["kernel_buckets"] = ks["size"]
        record["kernel_compile_s"] = ks["compile_s"]
    except Exception:
        pass


def main(final_attempt: bool = False) -> None:
    record = {
        "metric": "state_transitions_per_sec",
        "value": None,
        "unit": "states/sec",
        # measured: median host-only(proxy baseline, see BASELINE.md)
        # wall over median device wall on the corpus A/B
        "vs_baseline": None,
        "vs_baseline_def": "host_only_wall_s / corpus_wall_s (measured)",
        "n_lanes": N_LANES,
        "n_steps": N_STEPS,
        "bench_budget_s": BENCH_BUDGET_S,
        "headline_deadline_s": HEADLINE_DEADLINE_S,
        # mesh defaults so the fields exist even when the corpus half
        # never runs (budget-skipped records stay schema-complete)
        "mesh_devices": 1,
        "steal_count": 0,
        # telemetry defaults (ISSUE 7): populated by the corpus legs
        "solver_attribution": {},
        "trace_overlap_frac": 0.0,
        # flight-recorder defaults (ISSUE 8): refreshed at every emit
        "solver_loss_reasons": {},
        "captured_queries": 0,
        "cdcl_sat_verdicts": 0,
        # device-first funnel scorecard (ISSUE 9): refreshed at every
        # emit — device_sat / (device_sat + cdcl_sat) over the run
        "device_sat_verdicts": 0,
        "device_verdict_share": 0.0,
        # verdict-store scorecard (ISSUE 11): the duplicate-heavy leg
        # fills these; None = the leg never ran
        "store_hit_rate": None,
        "incremental_rate": None,
        "warm_hit_p50_s": None,
        # crash-safety scorecard (ISSUE 14): journal WAL overhead on
        # the warm admission tier + cumulative breaker trips
        # (refreshed at every emit; a healthy run reports 0 trips)
        "journal_overhead_frac": None,
        "breaker_trips": 0,
        # federated-serving scorecard (ISSUE 15): the fleet leg fills
        # these; None = the leg never ran
        "fleet_throughput_scale": None,
        "fleet_failover_p50_s": None,
        "fleet_reroute_dedup_rate": None,
        # chain-head streaming scorecard (ISSUE 16): the chainstream
        # leg fills these; None = the leg never ran
        "alert_p50_s": None,
        "head_lag_blocks_max": None,
        "reorg_recovery_s": None,
        "ingest_static_rate": None,
        # compile-plane scorecard (ISSUE 17): the compileplane leg
        # fills these; None = the leg never ran
        "cold_ready_no_pack_s": None,
        "cold_ready_pack_s": None,
        "kernel_pack_hit_rate": None,
        "aot_load_p50_s": None,
        # learned-router scorecard (ISSUE 19): the router A/B leg
        # fills these; None = the leg never ran (the compare gate
        # skips absent/None fields)
        "routed_speedup": None,
        "routing_regret": None,
        "router_artifact_version": None,
    }
    _mark_solver_run()
    capture_dir = os.environ.get("MYTHRIL_BENCH_CAPTURE_DIR")
    if capture_dir:
        # leave a hard-query corpus behind for solverlab tuning
        # (ROADMAP item 1): every query this bench solves becomes a
        # replayable artifact
        try:
            from mythril_tpu import observe as _observe

            _observe.configure_capture(capture_dir)
            record["capture_dir"] = capture_dir
        except Exception as e:
            print(f"bench: query capture unavailable: {e!r}", file=sys.stderr)
    if os.environ.get("MYTHRIL_BENCH_NO_OBSERVE"):
        # the telemetry-overhead differential leg: spans/attribution/
        # routing recording off, record fields stay at their defaults
        from mythril_tpu import observe

        observe.set_enabled(False)

    try:
        record.update(bench_static_prune())
        print("bench: static prune done", file=sys.stderr)
    except Exception as e:
        print(f"bench: static-prune half failed: {e!r}", file=sys.stderr)
        record["static_prune_rate"] = None
        record["static_answer_rate"] = None
        record["screen_mount_rate_opcode"] = None
        record["screen_mount_rate_semantic"] = None
        record["link_resolve_rate"] = None
        record["proxy_detect_rate"] = None
        record["callgraph_fingerprint_hit_rate"] = None
        record["static_link_wall_s"] = None

    try:
        record.update(bench_journal())
        print("bench: journal leg done", file=sys.stderr)
    except Exception as e:
        print(f"bench: journal leg failed: {e!r}", file=sys.stderr)

    try:
        record.update(
            _with_deadline(bench_chainstream, 120)
        )
        print("bench: chainstream leg done", file=sys.stderr)
    except _Deadline:
        print("bench: chainstream leg hit its deadline", file=sys.stderr)
    except Exception as e:
        print(f"bench: chainstream leg failed: {e!r}", file=sys.stderr)

    # the compile-plane leg runs EARLY (it clears the jit caches to
    # simulate a fresh replica — later legs recompile their own shapes
    # regardless, earlier ones must not have theirs dropped mid-use)
    if _budget_left() > 240 and not os.environ.get(
        "MYTHRIL_BENCH_NO_COMPILEPLANE"
    ):
        try:
            record.update(
                _with_deadline(bench_compileplane, 180)
            )
            print("bench: compileplane leg done", file=sys.stderr)
        except _Deadline:
            print("bench: compileplane leg hit its deadline", file=sys.stderr)
        except Exception as e:
            print(f"bench: compileplane leg failed: {e!r}", file=sys.stderr)

    # the routed-vs-uniform tier-ladder A/B (ISSUE 19): two corpus
    # passes on a trimmed corpus + an in-process train step between
    if _budget_left() > 300 and not os.environ.get(
        "MYTHRIL_BENCH_NO_ROUTER"
    ):
        try:
            record.update(
                _with_deadline(
                    bench_router,
                    max(120, min(600, int(_budget_left() - 120))),
                )
            )
            print("bench: router leg done", file=sys.stderr)
        except _Deadline:
            print("bench: router leg hit its deadline", file=sys.stderr)
        except Exception as e:
            print(f"bench: router leg failed: {e!r}", file=sys.stderr)

    if _budget_left() > 240 and not os.environ.get(
        "MYTHRIL_BENCH_NO_FLEET"
    ):
        try:
            record.update(
                _with_deadline(
                    bench_fleet,
                    max(60, min(300, int(_budget_left() - 120))),
                )
            )
            print("bench: fleet leg done", file=sys.stderr)
        except _Deadline:
            print("bench: fleet leg hit the budget", file=sys.stderr)
        except Exception as e:
            print(f"bench: fleet leg failed: {e!r}", file=sys.stderr)

    dev = {}
    try:
        dev = _with_deadline(
            bench_transitions, max(30, min(240, int(_budget_left() - 60)))
        )
    except _Deadline:
        print("bench: transitions half hit the budget", file=sys.stderr)
        dev = {"transitions": "deadline"}
    except Exception:
        if not final_attempt:
            raise  # linearity-gate rejection: let __main__ retry
        import traceback as _tb

        print(
            f"bench: transitions half failed: {_tb.format_exc()}",
            file=sys.stderr,
        )
        dev = {"transitions": "failed"}
    if "transitions" in dev:
        record["transitions"] = dev["transitions"]
    record["scaling_ratio_4x_steps"] = (
        round(dev["scaling_ratio"], 2) if "scaling_ratio" in dev else None
    )
    for k in (
        "state_bytes_per_lane", "bytes_per_step", "batch_steps_per_sec",
        "hbm_demand_gbps", "hbm_utilization_pct", "mfu_pct",
        "roofline_bound",
    ):
        if k in dev:
            record[k] = dev[k]

    # -- generic-vs-specialized step-throughput A/B -------------------
    if "rate" not in dev or _budget_left() < 120:
        record["specialize_ab"] = (
            "budget-skipped" if "rate" in dev else "no-generic-leg"
        )
        print("bench: specialize A/B skipped", file=sys.stderr)
    else:
        try:
            record.update(
                _with_deadline(
                    lambda: bench_specialize_ab(dev),
                    max(30, min(180, int(_budget_left() - 60))),
                )
            )
        except _Deadline:
            record["specialize_ab"] = "deadline"
            print("bench: specialize A/B hit its deadline", file=sys.stderr)
        except Exception as e:
            record["specialize_ab"] = "failed"
            print(f"bench: specialize A/B failed: {e!r}", file=sys.stderr)

    # -- specialized-vs-blockjit step-throughput A/B (ISSUE 13) -------
    if _budget_left() < 120:
        record["blockjit_ab"] = "budget-skipped"
        print("bench: blockjit A/B skipped", file=sys.stderr)
    else:
        try:
            record.update(
                _with_deadline(
                    bench_blockjit_ab,
                    max(30, min(300, int(_budget_left() - 60))),
                )
            )
        except _Deadline:
            record["blockjit_ab"] = "deadline"
            print("bench: blockjit A/B hit its deadline", file=sys.stderr)
        except Exception as e:
            record["blockjit_ab"] = "failed"
            print(f"bench: blockjit A/B failed: {e!r}", file=sys.stderr)

    # -- headline convergence pair (bounded by the headline window) ---
    conv = None
    if CONV_PAIRS < 1:
        record["corpus"] = "disabled"
    elif _budget_left() < 120 or _headline_left() < 60:
        record["corpus"] = "budget-skipped"
        print("bench: corpus half skipped (budget spent)", file=sys.stderr)
    else:
        try:
            conv = _ConvAB()
            if not conv.contracts:
                record["corpus"] = "empty"
                conv = None
            else:
                conv.warmup()
                conv.run_pair(headline=True)
                record.update(conv.summarize(strict=False))
        except _Deadline:
            print("bench: a corpus leg hit its deadline", file=sys.stderr)
            record["corpus"] = "deadline"
        except Exception as e:
            # the corpus half must not sink the device metric: any
            # other bug is recorded as a skip, the JSON still prints
            print(f"bench: corpus half failed: {e!r}", file=sys.stderr)
            record["corpus"] = "failed"
            conv = None

    _refresh_headline(record, dev)
    _emit(record, "headline")  # <-- the capture-window guarantee

    # -- refinement: the remaining pairs, then the cheap halves -------
    spread_error = None
    while (
        conv is not None
        and len(conv.host_legs) < CONV_PAIRS
        and _budget_left() >= 120
    ):
        try:
            conv.run_pair()
            record.update(conv.summarize(strict=not final_attempt))
        except _Deadline:
            print("bench: a corpus leg hit its deadline", file=sys.stderr)
            break
        except RuntimeError as why:
            # spread-gate rejection: finish the record (the headline
            # line already stands), then let __main__ retry the whole
            # measurement unless this IS the retry
            record.update(conv.summarize(strict=False))
            spread_error = why
            break

    # -- duplicate-heavy verdict-store leg ----------------------------
    if _budget_left() < 90:
        record.setdefault("store", "budget-skipped")
        print("bench: store leg skipped (budget spent)", file=sys.stderr)
    else:
        try:
            record.update(
                _with_deadline(
                    lambda: bench_store(
                        budget_s=max(45, min(150, int(_budget_left() - 60)))
                    ),
                    max(60, min(180, int(_budget_left() - 45))),
                )
            )
        except _Deadline:
            record["store"] = "deadline"
            print("bench: store leg hit its deadline", file=sys.stderr)
        except Exception as e:
            record["store"] = "failed"
            print(f"bench: store leg failed: {e!r}", file=sys.stderr)

    if _budget_left() < 60:
        record.setdefault("default_path", "budget-skipped")
        print(
            "bench: default-path half skipped (budget spent)",
            file=sys.stderr,
        )
    else:
        try:
            record.update(
                bench_device_default_path(
                    budget_s=max(30, min(210, int(_budget_left() - 45)))
                )
            )
        except Exception as e:
            print(
                f"bench: default-path half failed: {e!r}", file=sys.stderr
            )
    if _budget_left() < 45:
        record.setdefault("hard_solve", "budget-skipped")
        print(
            "bench: hard-solve half skipped (budget spent)", file=sys.stderr
        )
    else:
        try:
            record.update(
                bench_hard_solve(
                    budget_s=max(20, min(300, int(_budget_left() - 15)))
                )
            )
        except Exception as e:
            print(f"bench: hard-solve half failed: {e!r}", file=sys.stderr)

    trace_out = os.environ.get("MYTHRIL_BENCH_TRACE_OUT")
    if trace_out:
        # the run's Perfetto timeline beside the record: a pipelined
        # multi-device corpus leg renders its overlapped waves
        try:
            from mythril_tpu import observe

            observe.export_trace(trace_out)
            record["trace_out"] = trace_out
            print(f"bench: span trace written to {trace_out}",
                  file=sys.stderr)
        except Exception as e:
            print(f"bench: trace export failed: {e!r}", file=sys.stderr)

    _refresh_headline(record, dev)
    _emit(record, "final")
    if spread_error is not None and not final_attempt:
        raise spread_error  # __main__ reruns; this record already printed


if __name__ == "__main__":
    # One retry shields the round's metric from transient device/tunnel
    # hiccups and from a spread-gate rejection. Only runtime/IO errors
    # retry; deterministic bugs propagate.
    try:
        main()
    except (RuntimeError, OSError) as e:
        print(f"bench: first attempt failed ({e!r}); retrying", file=sys.stderr)
        time.sleep(5)
        main(final_attempt=True)
