"""Benchmark: honest batched-interpreter throughput + the driver metric.

Two measurements, one JSON line:

1. `state_transitions_per_sec` (headline `value`): one state-transition
   = one EVM instruction applied to one path state — the unit of work of
   the reference's `execute_state` hot loop
   (mythril/laser/ethereum/svm.py:303). A single jit'd step advances
   every lane of a StateBatch at once on the TPU.

   Honesty rules (round-2 fix): on this platform `block_until_ready`
   returns before execution finishes, so timing stops only after a
   forced device->host readback (`np.asarray`) of the result, and the
   measurement is accepted only if wall time scales ~linearly with
   `max_steps` (a dispatch-only "measurement" would not).

2. `contracts_per_sec` / `states_per_sec` (extra fields): the
   BASELINE.json driver metric — the full `myth analyze`-equivalent
   pipeline at -t 2 over the reference's precompiled contract corpus
   (tests/testdata/inputs/*.sol.o).

Baseline: the reference engine executes ~2,000 state-transitions/sec
single-threaded (order-of-magnitude from its own instruction-profiler
machinery; it publishes no numbers — see BASELINE.md — and cannot run
in this image since z3 is not installed). vs_baseline uses that
documented nominal figure against the honest transitions/sec.
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_STATES_PER_SEC = 2_000.0
N_LANES = 16384
N_STEPS = 256
CORPUS_TIMEOUT_S = 45


def _timed_run(batch, code, max_steps: int) -> float:
    """Run the batched interpreter and return wall seconds measured
    through a forced host readback (the only sync this platform
    honors)."""
    import numpy as np

    from mythril_tpu.laser.batch.run import run

    t0 = time.perf_counter()
    out, steps = run(batch, code, max_steps=max_steps)
    # np.asarray forces device execution AND the device->host copy;
    # summing both fields makes the readback depend on the full result.
    sync = int(np.asarray(out.pc).sum())
    n_live = int((np.asarray(out.status) == 0).sum())
    dt = time.perf_counter() - t0
    assert sync >= 0  # keep the readback live
    assert int(steps) == max_steps, f"early halt at {int(steps)}/{max_steps}"
    # the demo contract loops forever; a dead lane means transitions
    # would overcount masked no-op work
    assert n_live == out.pc.shape[0], f"lanes died: {n_live}/{out.pc.shape[0]}"
    return dt


def bench_transitions() -> dict:
    import jax

    from __graft_entry__ import _demo_workload

    batch, code = _demo_workload(N_LANES)

    # Warmup at both step counts so neither timed call includes compile.
    _timed_run(batch, code, N_STEPS)
    _timed_run(batch, code, N_STEPS // 4)

    dt_full = _timed_run(batch, code, N_STEPS)
    dt_quarter = _timed_run(batch, code, N_STEPS // 4)

    # Linearity gate: 4x the steps must cost >=2x the wall time (slack
    # for fixed dispatch/readback overhead). A lazy "finish" fails this.
    # The upper bound catches the opposite failure: a transient tunnel
    # stall during the full run (observed once: ratio 19.4, recorded
    # rate understated 5x) — raise so the __main__ retry reruns clean.
    ratio = dt_full / max(dt_quarter, 1e-9)
    if ratio < 2.0:
        raise RuntimeError(
            f"non-linear scaling (t({N_STEPS})={dt_full:.3f}s vs "
            f"t({N_STEPS // 4})={dt_quarter:.3f}s, ratio {ratio:.2f}) — "
            "the timer is not observing execution"
        )
    if ratio > 8.0:
        raise RuntimeError(
            f"full run stalled (ratio {ratio:.2f} for 4x steps) — "
            "transient device/link interference; retrying gives an "
            "honest number instead of an understated one"
        )

    transitions = N_LANES * N_STEPS
    rate = transitions / dt_full
    print(
        f"bench: {transitions} transitions in {dt_full:.3f}s "
        f"(quarter-run {dt_quarter:.3f}s, ratio {ratio:.2f}) on "
        f"{jax.devices()[0]}",
        file=sys.stderr,
    )
    return {"rate": rate, "wall_s": dt_full, "scaling_ratio": ratio}


def bench_corpus() -> dict:
    """Driver metric: contracts/sec + states/sec at -t 2 over the
    reference's precompiled corpus, via the real analyzer pipeline.

    Both legs of the A/B run at EQUAL per-contract budgets: the
    device leg is the default path (striped corpus prepass on the
    chip + host analyses consuming its witnesses/coverage), the
    host-only leg switches the device off. Headline numbers come from
    the device leg; the host-only fields make the comparison honest
    rather than implied."""
    from pathlib import Path

    ref = Path(os.environ.get("MYTHRIL_REFERENCE_DIR", "/root/reference"))
    inputs = ref / "tests" / "testdata" / "inputs"
    files = sorted(inputs.glob("*.sol.o"))
    if not files:
        return {}

    import logging

    logging.disable(logging.WARNING)
    try:
        from mythril_tpu.analysis.corpus import analyze_corpus

        contracts = [(f.read_text().strip(), "", f.stem) for f in files]

        def leg(use_device):
            # equal-budget AND equal-cache: the legs share one process,
            # and get_model's memo is keyed on hash-consed term ids that
            # are identical across legs — without this reset the second
            # leg would ride the first leg's solves
            from mythril_tpu.support.model import clear_cache

            clear_cache()
            t0 = time.perf_counter()
            results = analyze_corpus(
                contracts,
                transaction_count=2,
                execution_timeout=CORPUS_TIMEOUT_S,
                create_timeout=10,
                use_device=use_device,  # None = the default (auto) path
            )
            dt = time.perf_counter() - t0
            return {
                "wall_raw": dt,
                "wall_s": round(dt, 1),
                "states": sum(r.get("states", 0) for r in results),
                "issues": sum(len(r["issues"]) for r in results),
                "errors": [r["name"] for r in results if r["error"]],
                # the prepass stats block is corpus-wide (one striped
                # exploration shared by all contracts): max, not sum
                "prepass_steps": max(
                    (
                        (r.get("device_prepass") or {}).get("device_steps", 0)
                        for r in results
                    ),
                    default=0,
                ),
            }

        device = leg(use_device=None)  # auto: on with an accelerator
        host = leg(use_device=False)
    finally:
        logging.disable(logging.NOTSET)

    print(
        f"bench: corpus {len(files)} contracts — device leg "
        f"{device['wall_s']}s/{device['issues']} issues, host-only leg "
        f"{host['wall_s']}s/{host['issues']} issues",
        file=sys.stderr,
    )
    return {
        "contracts_per_sec": round(len(files) / device["wall_raw"], 3),
        "states_per_sec": round(device["states"] / device["wall_raw"], 1),
        "corpus_contracts": len(files),
        "corpus_wall_s": device["wall_s"],
        "corpus_issues": device["issues"],
        "corpus_errors": len(device["errors"]),
        "corpus_prepass_lane_steps": device["prepass_steps"],
        "host_only_wall_s": host["wall_s"],
        "host_only_issues": host["issues"],
        "host_only_states_per_sec": round(host["states"] / host["wall_raw"], 1),
        "device_extra_issues": device["issues"] - host["issues"],
    }


def bench_device_default_path(budget_s: int = 210) -> dict:
    """The default `myth analyze` path with the device engaged: one
    reference contract analyzed single-process, reporting how much
    stepping/solving the TPU did (device prepass + portfolio-first
    feasibility, both on by default off-CPU).

    Runs last, under a SIGALRM deadline: the device kernels'
    first-compile cost must never sink the earlier metrics (this
    process owns the chip, so a subprocess cannot do the work)."""
    import signal
    from pathlib import Path

    ref = Path(os.environ.get("MYTHRIL_REFERENCE_DIR", "/root/reference"))
    target = ref / "tests" / "testdata" / "inputs" / "exceptions.sol.o"
    if not target.exists():
        return {}

    class _Deadline(Exception):
        pass

    def _alarm(signum, frame):
        raise _Deadline()

    import logging

    previous = signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(budget_s)
    logging.disable(logging.WARNING)
    try:
        from mythril_tpu.analysis.corpus import analyze_corpus
        from mythril_tpu.laser.smt.solver.solver_statistics import (
            SolverStatistics,
        )

        stats = SolverStatistics()
        stats.enabled = True
        t0 = time.perf_counter()
        results = analyze_corpus(
            [(target.read_text().strip(), "", target.stem)],
            transaction_count=2,
            execution_timeout=30,
            create_timeout=10,
            processes=1,
        )
        out = {
            "default_path_wall_s": round(time.perf_counter() - t0, 1),
            "default_path_issues": len(results[0]["issues"]),
            "device_sat_verdicts": stats.device_sat_count,
            "cdcl_sat_verdicts": stats.cdcl_sat_count,
        }
        for k, v in (results[0].get("device_prepass") or {}).items():
            out[f"prepass_{k}"] = v
    except _Deadline:
        print("bench: default-path half hit its deadline", file=sys.stderr)
        return {"default_path": "deadline"}
    except Exception as e:
        print(f"bench: default-path half skipped: {e!r}", file=sys.stderr)
        return {"default_path": "skipped"}
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, previous)
        logging.disable(logging.NOTSET)
    print(f"bench: default path {out}", file=sys.stderr)
    return out


def main() -> None:
    dev = bench_transitions()
    corpus = {}
    try:
        corpus = bench_corpus()
    except Exception as e:  # corpus half must not sink the device metric
        print(f"bench: corpus half failed: {e!r}", file=sys.stderr)
    default_path = {}
    try:
        default_path = bench_device_default_path()
    except Exception as e:
        print(f"bench: default-path half failed: {e!r}", file=sys.stderr)

    record = {
        "metric": "state_transitions_per_sec",
        "value": round(dev["rate"], 1),
        "unit": "states/sec",
        "vs_baseline": round(dev["rate"] / BASELINE_STATES_PER_SEC, 2),
        "scaling_ratio_4x_steps": round(dev["scaling_ratio"], 2),
        "n_lanes": N_LANES,
        "n_steps": N_STEPS,
    }
    record.update(corpus)
    record.update(default_path)
    print(json.dumps(record))


if __name__ == "__main__":
    # One retry shields the round's metric from transient device/tunnel
    # hiccups (observed once right after a heavy test run released the
    # chip). Only runtime/IO errors retry; deterministic bugs propagate.
    try:
        main()
    except (RuntimeError, OSError) as e:
        print(f"bench: first attempt failed ({e!r}); retrying", file=sys.stderr)
        time.sleep(5)
        main()
