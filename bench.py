"""Benchmark: batched interpreter throughput in state-transitions/sec.

One state-transition = one EVM instruction applied to one path state —
the unit of work of the reference's `execute_state` hot loop
(mythril/laser/ethereum/svm.py:303), which processes exactly one per
Python-interpreter iteration. Here a single jit'd step advances every
lane of a StateBatch at once on the TPU.

Baseline: the reference engine executes ~2,000 state-transitions/sec
single-threaded (order-of-magnitude from its own instruction-profiler
machinery; it publishes no numbers — see BASELINE.md — and cannot run
in this image since z3 is not installed). vs_baseline uses that
documented nominal figure.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

BASELINE_STATES_PER_SEC = 2_000.0
N_LANES = 16384
N_STEPS = 1024


def main() -> None:
    import jax

    from __graft_entry__ import _demo_workload
    from mythril_tpu.laser.batch.run import run

    batch, code = _demo_workload(N_LANES)

    # warmup / compile — same static max_steps as the timed call, or the
    # timed region would include a fresh trace+compile
    out, steps = run(batch, code, max_steps=N_STEPS)
    jax.block_until_ready(out)

    t0 = time.perf_counter()
    out, steps = run(batch, code, max_steps=N_STEPS)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    # the demo contract loops forever, so every lane stays live
    n_live = int((out.status == 0).sum())
    assert n_live == N_LANES, f"lanes died: {n_live}/{N_LANES}"
    transitions = N_LANES * int(steps)
    rate = transitions / dt

    print(
        f"bench: {transitions} transitions in {dt:.3f}s on "
        f"{jax.devices()[0]}", file=sys.stderr)
    print(json.dumps({
        "metric": "state_transitions_per_sec",
        "value": round(rate, 1),
        "unit": "states/sec",
        "vs_baseline": round(rate / BASELINE_STATES_PER_SEC, 2),
    }))


if __name__ == "__main__":
    # one retry shields the round's metric from transient device/tunnel
    # hiccups (observed once right after a heavy test run released the
    # chip)
    try:
        main()
    except Exception as e:
        print(f"bench: first attempt failed ({e!r}); retrying", file=sys.stderr)
        time.sleep(5)
        main()
