"""Top-level exception types.

Reference parity: mythril/exceptions.py:1-48.
"""


class MythrilBaseException(Exception):
    """The base exception for the framework."""


class CompilerError(MythrilBaseException):
    """Solc compilation failed."""


class UnsatError(MythrilBaseException):
    """A solver query had no model (reference: mythril/exceptions.py)."""


class SolverTimeOutException(UnsatError):
    """A solver query timed out (treated as unsat by issue builders)."""


class NoContractFoundError(MythrilBaseException):
    """The supplied input contained no contract."""


class CriticalError(MythrilBaseException):
    """Fatal user-facing error; the CLI prints it and exits."""


class AddressNotFoundError(MythrilBaseException):
    """The searched address was not found."""


class DetectorNotFoundError(MythrilBaseException):
    """An unknown detection module name was requested."""


class IllegalArgumentError(ValueError):
    """An argument combination is invalid."""
