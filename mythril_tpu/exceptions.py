"""Top-level exception types.

Reference parity: mythril/exceptions.py:1-48.
"""


class MythrilBaseException(Exception):
    """The base exception for the framework."""


class CompilerError(MythrilBaseException):
    """Solc compilation failed."""


class UnsatError(MythrilBaseException):
    """A solver query had no model (reference: mythril/exceptions.py)."""


class SolverTimeOutException(UnsatError):
    """A solver query timed out (treated as unsat by issue builders)."""


class NoContractFoundError(MythrilBaseException):
    """The supplied input contained no contract."""


class CriticalError(MythrilBaseException):
    """Fatal user-facing error; the CLI prints it and exits."""


class AddressNotFoundError(MythrilBaseException):
    """The searched address was not found."""


class DetectorNotFoundError(MythrilBaseException):
    """An unknown detection module name was requested."""


class IllegalArgumentError(ValueError):
    """An argument combination is invalid."""


# -- resilience taxonomy (support/resilience.py) ---------------------------
# Resource exhaustion and infrastructure faults are first-class
# OUTCOMES of an analysis, not crashes: these types carry the fault to
# the supervisor layer, which degrades the affected lane/contract and
# keeps the corpus running.


class DeadlineExpiredError(MythrilBaseException):
    """The run's wall-clock deadline expired (--deadline with
    --on-timeout=fail; partial mode reports instead of raising)."""


class WatchdogTimeout(MythrilBaseException):
    """A guarded native call wedged past its watchdog budget and was
    abandoned — the callee's state (e.g. a CDCL clause session) must be
    treated as lost and rebuilt."""


class DeviceDispatchError(MythrilBaseException):
    """A device dispatch kept failing after retries and the reduced-
    capacity fallback — the caller degrades the work to the host."""


class InjectedFault(MythrilBaseException):
    """A deterministic fault fired by the injection harness
    (support/resilience.py arm_fault). Never raised in production runs."""

    def __init__(self, site: str, detail: str = ""):
        super().__init__(f"injected fault at {site}" + (f": {detail}" if detail else ""))
        self.site = site
