"""Top-level plugin loader dispatching by plugin kind.

Reference parity: mythril/plugin/loader.py:22-80 — detection modules
register with the ModuleLoader; laser plugins with the
LaserPluginLoader; instantiated once at CLI import.
"""

from __future__ import annotations

import logging
from typing import Dict

from mythril_tpu.analysis.module import DetectionModule
from mythril_tpu.analysis.module.loader import ModuleLoader
from mythril_tpu.laser.plugin.loader import LaserPluginLoader
from mythril_tpu.plugin.discovery import PluginDiscovery
from mythril_tpu.plugin.interface import MythrilLaserPlugin, MythrilPlugin
from mythril_tpu.support.support_utils import Singleton

log = logging.getLogger(__name__)


class UnsupportedPluginType(Exception):
    """A plugin of an unsupported kind was loaded."""


class MythrilPluginLoader(object, metaclass=Singleton):
    """Loads MythrilPlugins, dispatching to kind-specific loaders."""

    def __init__(self):
        log.info("Initializing mythril plugin loader")
        self.loaded_plugins = []
        self.plugin_args: Dict[str, Dict] = dict()
        self._load_default_enabled()

    def set_args(self, plugin_name: str, **kwargs):
        self.plugin_args[plugin_name] = kwargs

    def load(self, plugin: MythrilPlugin):
        if not isinstance(plugin, MythrilPlugin):
            raise ValueError("Passed plugin is not of type MythrilPlugin")
        log.info("Loading plugin: %s", str(plugin))

        if isinstance(plugin, DetectionModule):
            self._load_detection_module(plugin)
        elif isinstance(plugin, MythrilLaserPlugin):
            self._load_laser_plugin(plugin)
        else:
            raise UnsupportedPluginType("Passed plugin type is not yet supported")

        self.loaded_plugins.append(plugin)
        log.info("Finished loading plugin: %s", plugin.name)

    @staticmethod
    def _load_detection_module(plugin) -> None:
        log.info("Loading detection module: %s", plugin.name)
        ModuleLoader().register_module(plugin)

    @staticmethod
    def _load_laser_plugin(plugin) -> None:
        log.info("Loading laser plugin: %s", plugin.name)
        LaserPluginLoader().load(plugin)

    def _load_default_enabled(self) -> None:
        log.info("Loading installed analysis modules that are enabled by default")
        for plugin_name in PluginDiscovery().get_plugins(default_enabled=True):
            plugin = PluginDiscovery().build_plugin(
                plugin_name, self.plugin_args.get(plugin_name, {})
            )
            self.load(plugin)
