"""Entry-point based plugin discovery.

Reference parity: mythril/plugin/discovery.py:9-58 — loads every
package exposing a `mythril.plugins` setuptools entry point (the same
group name is kept so existing third-party plugin packages resolve).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from mythril_tpu.plugin.interface import MythrilPlugin
from mythril_tpu.support.support_utils import Singleton

ENTRY_POINT_GROUP = "mythril.plugins"


class PluginDiscovery(object, metaclass=Singleton):
    """Discovers and builds plugins from installed python packages."""

    _installed_plugins: Optional[Dict[str, Any]] = None

    def init_installed_plugins(self) -> None:
        try:
            from importlib.metadata import entry_points

            eps = entry_points()
            if hasattr(eps, "select"):
                group = eps.select(group=ENTRY_POINT_GROUP)
            else:
                group = eps.get(ENTRY_POINT_GROUP, [])
            self._installed_plugins = {ep.name: ep.load() for ep in group}
        except Exception:
            self._installed_plugins = {}

    @property
    def installed_plugins(self):
        if self._installed_plugins is None:
            self.init_installed_plugins()
        return self._installed_plugins

    def is_installed(self, plugin_name: str) -> bool:
        return plugin_name in self.installed_plugins.keys()

    def build_plugin(self, plugin_name: str, plugin_args: Dict) -> MythrilPlugin:
        if not self.is_installed(plugin_name):
            raise ValueError(f"Plugin with name: `{plugin_name}` is not installed")
        plugin = self.installed_plugins.get(plugin_name)
        if plugin is None or not issubclass(plugin, MythrilPlugin):
            raise ValueError(f"No valid plugin was found for {plugin_name}")
        return plugin(**plugin_args)

    def get_plugins(self, default_enabled=None) -> List[str]:
        if default_enabled is None:
            return list(self.installed_plugins.keys())
        return [
            plugin_name
            for plugin_name, plugin_class in self.installed_plugins.items()
            if getattr(plugin_class, "plugin_default_enabled", False)
            == default_enabled
        ]
