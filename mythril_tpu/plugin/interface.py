"""Third-party extension interfaces.

Reference parity: mythril/plugin/interface.py:5-45 — `MythrilPlugin`
(metadata base), `MythrilCLIPlugin`, and `MythrilLaserPlugin` (a
MythrilPlugin that is also a laser PluginBuilder).
"""

from __future__ import annotations

from abc import ABC

from mythril_tpu.laser.plugin.builder import PluginBuilder as LaserPluginBuilder


class MythrilPlugin:
    """Base for installable extensions: laser plugins, strategies,
    detection modules, or CLI commands."""

    author = "Default Author"
    name = "Plugin Name"
    plugin_license = "All rights reserved."
    plugin_type = "Mythril Plugin"
    plugin_version = "0.0.1 "
    plugin_description = "This is an example plugin description"
    plugin_default_enabled = False

    def __init__(self, **kwargs):
        pass

    def __repr__(self):
        plugin_name = type(self).__name__
        return f"{plugin_name} - {self.plugin_version} - {self.author}"


class MythrilCLIPlugin(MythrilPlugin):
    """Adds commands to the CLI."""


class MythrilLaserPlugin(MythrilPlugin, LaserPluginBuilder, ABC):
    """Instruments the laser EVM."""
