"""First-party example extension registered under the
``mythril.plugins`` entry-point group (pyproject.toml).

Two jobs:

1. make L10 reachable in practice — once this package is installed,
   ``PluginDiscovery`` finds a real entry point instead of an empty
   group (the reference ships its extension group the same way,
   /root/reference/setup.py entry_points);
2. serve as the template third-party plugin authors copy: a
   ``MythrilLaserPlugin`` is simultaneously package metadata (author,
   version, default-enabled flag) and a laser ``PluginBuilder`` whose
   built plugin instruments the symbolic VM through hooks.
"""

from __future__ import annotations

import logging

from mythril_tpu.laser.plugin.interface import LaserPlugin
from mythril_tpu.plugin.interface import MythrilLaserPlugin

log = logging.getLogger(__name__)


class _CoverageMetrics(LaserPlugin):
    """Counts executed instructions and distinct jump destinations per
    symbolic VM run and logs the totals when execution stops."""

    def __init__(self) -> None:
        self.instructions = 0
        self.jumpdests = set()

    def initialize(self, symbolic_vm) -> None:
        self.instructions = 0
        self.jumpdests = set()

        @symbolic_vm.laser_hook("execute_state")
        def on_state(global_state):
            self.instructions += 1
            try:
                if global_state.get_current_instruction()["opcode"] == "JUMPDEST":
                    self.jumpdests.add(global_state.mstate.pc)
            except IndexError:
                pass

        @symbolic_vm.laser_hook("stop_sym_exec")
        def on_stop():
            log.info(
                "coverage-metrics: %d instructions executed, %d distinct "
                "JUMPDESTs reached",
                self.instructions,
                len(self.jumpdests),
            )


class CoverageMetricsPlugin(MythrilLaserPlugin):
    """The installable wrapper (entry point: ``coverage-metrics``)."""

    def __init__(self, **kwargs):
        # MythrilPlugin.__init__ does not chain to PluginBuilder's, so
        # without this the builder lacks `enabled` and
        # LaserPluginLoader.instrument_virtual_machine crashes
        super().__init__(**kwargs)
        self.enabled = True

    author = "mythril_tpu"
    name = "coverage-metrics"
    plugin_name = "coverage-metrics"
    plugin_license = "MIT"
    plugin_type = "Laser Plugin"
    plugin_version = "1.0.0"
    plugin_description = (
        "Example laser plugin: per-run instruction and JUMPDEST counters"
    )
    plugin_default_enabled = False

    def __call__(self, *args, **kwargs) -> LaserPlugin:
        return _CoverageMetrics()
