"""`myth observe` operator tooling: the live top view, the static
digest report, and the bench-record trajectory/regression differ.

Three subcommands, all built on pure functions this module exposes so
the tests drive them without a terminal or an HTTP server:

- **top** — poll a running replica's ``/stats`` + ``/metrics`` and
  render a one-screen operator view: health state, queue/arena
  saturation, wave throughput, tier mix, solver funnel, device
  gauges.
- **report** — a markdown/HTML digest from a metrics snapshot (file
  or live scrape), the routing JSONL tail, and recent journeys: what
  the replica spent its life doing, for a postmortem or a capacity
  review.
- **compare** — diff BENCH_r* records into a trajectory table over
  the fields marked STABLE (backend-independent ratios and rates);
  ``--fail-on-regression`` exits nonzero when a stable field moves
  the wrong way past its threshold. Cross-backend fields
  (``device_verdict_share``, raw step rates, absolute walls) are
  carried in the table but never gated — the r05-vs-r06 CPU/TPU swap
  is the canonical counterexample.
"""

from __future__ import annotations

import json
import re
from typing import Dict, List, Optional, Tuple

#: (field, direction, relative threshold) rows gated by
#: `--fail-on-regression`. Direction "higher" fails when the newer
#: value drops more than threshold below the older; "lower" the
#: mirror. Thresholds are loose on noisy measurements, tight on
#: deterministic ones. Fields absent from either record are skipped
#: (the schema grew over rounds).
STABLE_FIELDS: Tuple[Tuple[str, str, float], ...] = (
    ("scaling_ratio_4x_steps", "higher", 0.15),
    ("specialize_speedup", "higher", 0.15),
    ("blockjit_speedup", "higher", 0.15),
    ("store_hit_rate", "higher", 0.10),
    ("incremental_rate", "higher", 0.10),
    ("warm_hit_p50_s", "lower", 0.50),
    # journal WAL overhead on the warm admission tier (ISSUE 14):
    # tiny absolute values, so the relative gate is loose — it exists
    # to catch the overhead DOUBLING, not wobbling
    ("journal_overhead_frac", "lower", 1.0),
    # fleet ratios (ISSUE 15): dedup rate is deterministic on the
    # bench's duplicate-heavy failover leg; throughput scale (2
    # replicas vs 1) wobbles with host load, so the gate is loose
    ("fleet_reroute_dedup_rate", "higher", 0.25),
    ("fleet_throughput_scale", "higher", 0.35),
    # chain-head streaming (ISSUE 16): the alert p50 is sub-ms on the
    # in-process leg, so the gate is loose — it catches the triage or
    # alert path gaining an order of magnitude, not scheduler wobble
    ("alert_p50_s", "lower", 0.50),
    # compile plane (ISSUE 17): pack hits over pack-consulting lookups
    # on the bench's bake->mount->first-wave leg — deterministic 1.0,
    # any drop means the artifact load path broke (absent in pre-r08
    # records: non-numeric values are exempt from the gate)
    ("kernel_pack_hit_rate", "higher", 0.10),
    ("static_answer_rate", "higher", 0.25),
    ("static_prune_rate", "higher", 0.50),
    # cross-contract linker (ISSUE 18): the planted fixture families
    # must keep resolving — the rate mixes in organic (unresolvable)
    # corpus edges, so the gate is loose; absent in pre-r08 records
    ("link_resolve_rate", "higher", 0.25),
    # learned tier router (ISSUE 19): routed-vs-uniform A/B on the
    # bench's mixed corpus — the routed leg must keep beating the
    # uniform one; wall ratios wobble with host load, so the gate is
    # loose; absent in pre-r19 records (skipped, like the linker rate)
    ("routed_speedup", "higher", 0.25),
    ("screen_mount_rate_semantic", "lower", 0.25),
    ("default_path_issues", "higher", 0.0),
    ("trace_overlap_frac", "higher", 0.25),
)

#: cross-backend / absolute-wall fields shown in the trajectory table
#: but exempt from the gate (r05 ran on TPU v5 lite, r06 on a
#: CPU-only container — raw rates are not comparable across rounds)
EXEMPT_FIELDS: Tuple[str, ...] = (
    "value", "vs_baseline", "device_verdict_share",
    "device_sat_verdicts", "cdcl_sat_verdicts", "contracts_per_sec",
    "corpus_wall_s", "host_only_wall_s", "specialized_step_rate",
    "blockjit_step_rate", "blockjit_block_rate", "spec_leg_step_rate",
    "generic_step_rate", "batch_steps_per_sec", "hbm_demand_gbps",
    "hbm_utilization_pct", "mfu_pct", "kernel_compile_s",
    "hard_solve_speedup", "fleet_failover_p50_s",
    "fleet_throughput_1r_wall_s", "fleet_throughput_2r_wall_s",
)


# ---------------------------------------------------------------------------
# Prometheus text parsing (the scrape side of top/report)
# ---------------------------------------------------------------------------
_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[^\s]+)$"
)
_LABEL = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> Dict[str, Dict[Tuple, float]]:
    """Prometheus text exposition -> {family: {label-key: value}}.
    Histogram _bucket/_sum/_count samples keep their suffixed family
    names; the label key is the sorted (k, v) tuple the registry
    uses."""
    out: Dict[str, Dict[Tuple, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        match = _SAMPLE.match(line)
        if not match:
            continue
        labels = tuple(sorted(
            (k, v.replace('\\"', '"').replace("\\\\", "\\"))
            for k, v in _LABEL.findall(match.group("labels") or "")
        ))
        try:
            value = float(match.group("value"))
        except ValueError:
            continue
        out.setdefault(match.group("name"), {})[labels] = value
    return out


def family_total(
    metrics: Dict[str, Dict], name: str, **labels
) -> float:
    """Sum of every sample of `name` whose labels contain `labels`."""
    want = set(labels.items())
    return sum(
        v for key, v in (metrics.get(name) or {}).items()
        if want <= set(key)
    )


# ---------------------------------------------------------------------------
# top
# ---------------------------------------------------------------------------
def _bar(fraction: float, width: int = 20) -> str:
    fraction = max(0.0, min(1.0, fraction))
    filled = int(round(fraction * width))
    return "[" + "#" * filled + "." * (width - filled) + "]"


def render_top(
    stats: Dict, metrics: Optional[Dict[str, Dict]] = None
) -> str:
    """One operator screen from a /stats payload (+ an optional parsed
    /metrics scrape for the health/device gauges)."""
    lines: List[str] = []
    health = stats.get("health") or {}
    state = health.get("state", "?")
    ready = health.get("ready")
    reasons = (
        health.get("reasons") or []
    ) + (health.get("not_ready_reasons") or [])
    lines.append(
        f"health   {state.upper():9s} ready={ready} "
        f"uptime={stats.get('uptime_s', '?')}s"
        + (f"  reasons: {', '.join(reasons)}" if reasons else "")
    )
    for status in health.get("objectives") or []:
        lines.append(
            "  slo %-24s %-9s burn %6.2f / %6.2f (short/long)"
            % (
                status.get("objective"), status.get("state"),
                status.get("burn_short", 0.0),
                status.get("burn_long", 0.0),
            )
        )
    queue = stats.get("queue") or {}
    depth, cap = queue.get("depth", 0), max(1, queue.get("capacity", 1))
    lines.append(
        f"queue    {_bar(depth / cap)} {depth}/{cap} "
        f"accepted={queue.get('accepted', 0)} "
        f"429={queue.get('rejected_full', 0)} "
        f"503={queue.get('rejected_draining', 0)}"
    )
    arena = stats.get("arena") or {}
    lanes, busy = max(1, arena.get("lanes", 1)), arena.get("lanes_busy", 0)
    lines.append(
        f"arena    {_bar(busy / lanes)} {busy}/{lanes} lanes, "
        f"jobs={arena.get('jobs_resident', 0)} "
        f"(max {arena.get('max_jobs_resident', 0)})"
    )
    waves = stats.get("waves") or {}
    lines.append(
        f"waves    {waves.get('count', 0)} total @ "
        f"{waves.get('rate_per_s', 0.0)}/s, warm "
        f"{waves.get('warm_wave_s')}s (cold {waves.get('cold_wave_s')}s)"
    )
    jobs = queue.get("jobs") or {}
    tier_mix = []
    store = stats.get("store") or {}
    static = stats.get("static") or {}
    tier_mix.append(f"store-hit={store.get('answered', 0)}")
    tier_mix.append(f"static-answer={static.get('static_answered', 0)}")
    tier_mix.append(f"done={jobs.get('done', 0)}")
    tier_mix.append(f"failed={jobs.get('failed', 0)}")
    lines.append("tiers    " + " ".join(tier_mix))
    solver = stats.get("solver") or {}
    if solver.get("loss"):
        top_loss = sorted(
            solver["loss"].items(), key=lambda kv: -kv[1]
        )[:3]
        lines.append(
            "solver   loss: "
            + ", ".join(f"{k}={v}" for k, v in top_loss)
        )
    device = stats.get("device") or {}
    if device:
        bits = []
        if "arena" in device:
            bits.append(f"occupancy={device['arena'].get('occupancy')}")
        if "host_rss_bytes" in device:
            bits.append(
                f"rss={device['host_rss_bytes'] / (1 << 20):.0f}MiB"
            )
        if "wave_overlap_frac" in device:
            bits.append(f"overlap={device['wave_overlap_frac']}")
        if "kernel_cache" in device:
            bits.append(
                f"kernels={device['kernel_cache'].get('size')} "
                f"(pinned {device['kernel_cache'].get('pinned')})"
            )
        lines.append("device   " + " ".join(bits))
    if metrics:
        state_value = family_total(metrics, "mtpu_health_state")
        lines.append(
            f"metrics  mtpu_health_state={int(state_value)} "
            f"families={len(metrics)}"
        )
    return "\n".join(lines)


def render_top_multi(
    rows: List[Tuple[str, Optional[Dict], Optional[Dict]]],
) -> str:
    """The fleet operator view: one health/occupancy column set per
    target. `rows` is (label, /stats payload or None, parsed /metrics
    or None) — a None stats renders the target as DOWN (the whole
    point of the view is seeing which replica is gone). A target that
    is itself a fleet front (its /stats carries a `fleet` block) gets
    its fleet counters as a detail line under the table."""
    header = (
        f"{'target':38s} {'health':9s} {'ready':5s} {'queue':9s} "
        f"{'lanes':9s} {'waves':6s} {'done/fail':9s} {'store':5s}"
    )
    lines = [header, "-" * len(header)]
    details: List[str] = []
    for label, stats, metrics in rows:
        name = label if len(label) <= 38 else "..." + label[-35:]
        if stats is None:
            lines.append(f"{name:38s} {'DOWN':9s} {'-':5s}")
            continue
        health = stats.get("health") or {}
        state = str(health.get("state", "?")).upper()
        ready = "yes" if health.get("ready") else "no"
        queue = stats.get("queue") or {}
        arena = stats.get("arena") or {}
        jobs = queue.get("jobs") or {}
        store = stats.get("store") or {}
        lines.append(
            f"{name:38s} {state:9s} {ready:5s} "
            f"{queue.get('depth', 0)}/{queue.get('capacity', 0):<7} "
            f"{arena.get('lanes_busy', 0)}/{arena.get('lanes', 0):<7} "
            f"{(stats.get('waves') or {}).get('count', 0):<6} "
            f"{jobs.get('done', 0)}/{jobs.get('failed', 0):<7} "
            f"{store.get('answered', store.get('hits', 0))}"
        )
        reasons = (
            (health.get("reasons") or [])
            + (health.get("not_ready_reasons") or [])
        )
        if reasons:
            details.append(f"  {name}: " + ", ".join(reasons))
        fleet = stats.get("fleet")
        if fleet:
            details.append(
                f"  {name}: fleet submitted={fleet.get('submitted', 0)} "
                f"shed={fleet.get('shed', 0)} "
                f"failovers={fleet.get('failovers', 0)} "
                f"rerouted={fleet.get('rerouted', 0)} "
                f"reroute-deduped={fleet.get('reroute_deduped', 0)} "
                f"frontier-handoffs="
                f"{fleet.get('frontier_handoffs', 0)}"
            )
    return "\n".join(lines + details)


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------
def render_report(
    metrics: Optional[Dict[str, Dict]] = None,
    routing_records: Optional[List[Dict]] = None,
    journeys: Optional[List[Dict]] = None,
    stats: Optional[Dict] = None,
    fmt: str = "markdown",
) -> str:
    """The static digest: route mix and wall percentiles from the
    routing JSONL, health/device gauges from a metrics snapshot,
    journey tails. Markdown by default; fmt="html" wraps the same
    body in a minimal page."""
    lines: List[str] = ["# myth observe report", ""]
    if stats:
        health = stats.get("health") or {}
        lines += [
            "## Health",
            "",
            f"- state: **{health.get('state', '?')}** "
            f"(ready={health.get('ready')})",
            f"- uptime: {stats.get('uptime_s')}s, "
            f"draining: {stats.get('draining')}",
            "",
        ]
        for status in health.get("objectives") or []:
            lines.append(
                f"- objective `{status.get('objective')}`: "
                f"{status.get('state')} "
                f"(burn {status.get('burn_short')}/{status.get('burn_long')})"
            )
        lines.append("")
    if metrics:
        lines += ["## Metrics snapshot", ""]
        rows = [
            ("health state", family_total(metrics, "mtpu_health_state")),
            ("jobs settled",
             family_total(metrics, "mtpu_service_jobs_settled_total")),
            ("waves", family_total(metrics, "mtpu_service_waves_total")),
            ("store answered",
             family_total(metrics, "mtpu_service_store_answered_total")),
            ("static answered",
             family_total(metrics, "mtpu_service_static_answered_total")),
            ("solver queries",
             family_total(metrics, "mtpu_solver_queries_total")),
            ("device arena occupancy",
             family_total(metrics, "mtpu_device_arena_occupancy")),
        ]
        lines.append("| series | value |")
        lines.append("|---|---|")
        for label, value in rows:
            lines.append(f"| {label} | {value:g} |")
        lines.append("")
    if routing_records:
        routes: Dict[str, int] = {}
        walls: List[float] = []
        for rec in routing_records:
            outcome = rec.get("outcome") or {}
            routes[outcome.get("route", "?")] = (
                routes.get(outcome.get("route", "?"), 0) + 1
            )
            if isinstance(outcome.get("wall_s"), (int, float)):
                walls.append(float(outcome["wall_s"]))
        lines += ["## Routing mix", "", "| route | contracts |", "|---|---|"]
        for route, n in sorted(routes.items(), key=lambda kv: -kv[1]):
            lines.append(f"| {route} | {n} |")
        if walls:
            walls.sort()
            lines.append("")
            lines.append(
                f"wall p50 {walls[len(walls) // 2]:.3f}s, "
                f"p95 {walls[int(len(walls) * 0.95) - 1]:.3f}s "
                f"over {len(walls)} contracts"
            )
        lines.append("")
        # v4 linker feature columns — mean/max over the records that
        # carry them (v2-era tails have them None-filled; coverage
        # shows how much of the tail is post-linker)
        try:
            from mythril_tpu.observe.routing import V4_FEATURE_KEYS
        except Exception:
            V4_FEATURE_KEYS = ()
        link_rows = []
        for col in V4_FEATURE_KEYS:
            vals = [
                float(v)
                for rec in routing_records
                for v in [(rec.get("features") or {}).get(col)]
                if isinstance(v, (int, float))
                and not isinstance(v, bool)
            ]
            if vals:
                link_rows.append(
                    (col, sum(vals) / len(vals), max(vals), len(vals))
                )
        if link_rows:
            lines += [
                "## Link features",
                "",
                "| feature | mean | max | coverage |",
                "|---|---|---|---|",
            ]
            for col, mean, peak, n in link_rows:
                lines.append(
                    f"| {col} | {mean:.3f} | {peak:g} "
                    f"| {n}/{len(routing_records)} |"
                )
            lines.append("")
        # router digest: artifact version, routed/promoted mix, and —
        # when an artifact is mounted — model-priced regret over the
        # tail (evaluate_log). No artifact -> the mix alone.
        routed_n = sum(
            n for route, n in routes.items()
            if route.startswith("routed-")
        )
        promoted_n = sum(
            n for route, n in routes.items()
            if route.startswith("promoted-")
        )
        router = None
        try:
            from mythril_tpu.routing import (
                configured_router, evaluate_log,
            )

            router = configured_router()
        except Exception:
            router = None
        lines += ["## Router", ""]
        if router is not None:
            lines.append(f"- artifact: router-v{router.version}")
        else:
            lines.append("- artifact: none mounted")
        lines.append(
            f"- route mix: {routed_n} routed, {promoted_n} "
            f"promoted (of {len(routing_records)} records)"
        )
        if router is not None:
            try:
                ev = evaluate_log(routing_records, router)
                lines.append(
                    f"- regret: {ev['regret_s']:.3f}s over "
                    f"{ev['scored']} scored records, oracle "
                    f"agreement {ev['oracle_agreement']:.2f}"
                )
            except Exception:
                pass
        lines.append("")
    if journeys:
        lines += ["## Recent journeys", ""]
        for doc in journeys[-8:]:
            lines.append(
                f"- `{doc.get('journey_id')}`: "
                f"{' -> '.join(doc.get('tiers') or [])} "
                f"({doc.get('wall_s')}s)"
            )
        lines.append("")
    body = "\n".join(lines)
    if fmt == "html":
        paragraphs = body.replace("&", "&amp;").replace("<", "&lt;")
        return (
            "<!doctype html><html><head><meta charset='utf-8'>"
            "<title>myth observe report</title></head><body><pre>"
            + paragraphs + "</pre></body></html>"
        )
    return body


# ---------------------------------------------------------------------------
# compare
# ---------------------------------------------------------------------------
def load_bench_record(path: str) -> Tuple[str, Optional[Dict]]:
    """(label, parsed-record) from a BENCH_r*.json file. Accepts the
    driver envelope ({"n", "parsed", ...}) or a bare parsed dict;
    parsed=None (a timed-out round) comes back None and the caller
    skips it with a note."""
    with open(path) as fp:
        doc = json.load(fp)
    if isinstance(doc, dict) and "parsed" in doc:
        label = f"r{doc.get('n'):02d}" if doc.get("n") else path
        return label, doc["parsed"]
    return path, doc if isinstance(doc, dict) else None


def compare_records(
    records: List[Tuple[str, Optional[Dict]]],
    threshold_scale: float = 1.0,
) -> Dict:
    """Trajectory + regression analysis over two or more records (in
    chronological order). Gating is adjacent-pair over STABLE_FIELDS;
    `threshold_scale` multiplies every per-field threshold (CI can
    loosen or tighten the gate without editing the table)."""
    present = [(label, rec) for label, rec in records if rec]
    skipped = [label for label, rec in records if not rec]
    fields: List[str] = []
    seen = set()
    for name, _dir, _thr in STABLE_FIELDS:
        fields.append(name)
        seen.add(name)
    for _label, rec in present:
        for key in rec:
            value = rec[key]
            if (
                key not in seen
                and isinstance(value, (int, float))
                and not isinstance(value, bool)
            ):
                fields.append(key)
                seen.add(key)
    trajectory = {
        name: [
            rec.get(name) if isinstance(rec.get(name), (int, float))
            and not isinstance(rec.get(name), bool) else None
            for _label, rec in present
        ]
        for name in fields
    }
    regressions: List[Dict] = []
    directions = {name: (d, t) for name, d, t in STABLE_FIELDS}
    for i in range(1, len(present)):
        old_label, old = present[i - 1]
        new_label, new = present[i]
        for name, (direction, base_thr) in directions.items():
            before, after = old.get(name), new.get(name)
            if not isinstance(before, (int, float)) or not isinstance(
                after, (int, float)
            ):
                continue
            thr = base_thr * threshold_scale
            if direction == "higher":
                floor = before * (1.0 - thr)
                bad = after < floor - 1e-12
            else:
                ceiling = before * (1.0 + thr)
                bad = after > ceiling + 1e-12
            if bad:
                regressions.append({
                    "field": name,
                    "from": old_label,
                    "to": new_label,
                    "before": before,
                    "after": after,
                    "direction": direction,
                    "threshold": thr,
                })
    return {
        "labels": [label for label, _rec in present],
        "skipped": skipped,
        "trajectory": trajectory,
        "regressions": regressions,
        "stable_fields": [name for name, _d, _t in STABLE_FIELDS],
        "exempt_fields": list(EXEMPT_FIELDS),
    }


def render_compare(result: Dict) -> str:
    labels = result["labels"]
    lines = [
        "bench trajectory over " + " -> ".join(labels)
        + (
            f"  (skipped, no parsed record: {', '.join(result['skipped'])})"
            if result["skipped"] else ""
        ),
        "",
        "%-34s %s  gate" % ("field", "  ".join("%12s" % x for x in labels)),
    ]
    stable = set(result["stable_fields"])
    exempt = set(result["exempt_fields"])
    regressed = {r["field"] for r in result["regressions"]}

    def fmt(value) -> str:
        if value is None:
            return "%12s" % "-"
        if isinstance(value, float):
            return "%12.4g" % value
        return "%12d" % value

    for name, values in result["trajectory"].items():
        if all(v is None for v in values):
            continue
        if name in regressed:
            gate = "REGRESSED"
        elif name in stable:
            gate = "stable"
        elif name in exempt:
            gate = "exempt"
        else:
            gate = ""
        lines.append(
            "%-34s %s  %s"
            % (name, "  ".join(fmt(v) for v in values), gate)
        )
    if result["regressions"]:
        lines.append("")
        for reg in result["regressions"]:
            lines.append(
                "REGRESSION %s: %s %g -> %g (%s-is-better, "
                "threshold %.0f%%)"
                % (
                    reg["field"], f"{reg['from']}->{reg['to']}",
                    reg["before"], reg["after"], reg["direction"],
                    reg["threshold"] * 100,
                )
            )
    else:
        lines.append("")
        lines.append("no regressions on stable fields")
    return "\n".join(lines)
