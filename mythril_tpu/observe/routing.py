"""The routing feature log: one record per analyzed contract, joining
static-summary features with the analysis outcome.

ROADMAP item 5 wants a host/device routing cost model; a cost model
needs a training set. This module emits it: for every contract a
corpus run analyzes, one JSONL record holding

- **features** available BEFORE any routing decision — code size, CFG
  block/instruction counts, selector counts, storage-op density,
  screened-detector count, the kernel-specialization phase bucket;
- **outcome** observed AFTER — the route actually taken (device-owned
  / host walk / skipped), per-contract wall, waves, issues, verdicts.

`myth analyze --observe-out DIR` lands the records in
``DIR/routing_features.jsonl``; an in-memory tail is always kept (the
bench and the tests read it without touching disk). Schema is
versioned (`schema_version`) so the future trainer can pin it.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Dict, List, Optional

#: routing-record schema version — the routing log's OWN version, no
#: longer tied to the registry's. v2 adds the taint/value-set feature
#: block (taint_density, per-sink-kind tainted counts, resolved call
#: targets, fingerprint count, static answerability) and the
#: "static-answer" route. v3 adds the top-level ``journey_id`` — the
#: key that joins a record to its tier-ladder timeline
#: (observe/journey.py), so features ⨝ route ⨝ outcome ⨝ timeline
#: joins offline. v4 adds the cross-contract link feature block
#: (`V4_FEATURE_KEYS`): out-degree / resolved degree of the contract's
#: static call graph node, proxy classification flags, and the escape
#: -summary density — the "how entangled is this contract" axis the
#: cost model needs once multi-account arenas exist. v1/v2/v3 records
#: parse through `read_records` / `parse_record` unchanged (absent
#: features read as None; absent journey_id reads as None).
SCHEMA_VERSION = 4

#: every record carries exactly these top-level keys (the JSONL golden
#: test pins them); ``journey_id`` may be None for pre-v3 records
RECORD_KEYS = (
    "schema_version", "contract", "code_hash", "features", "outcome",
    "journey_id",
)

#: feature keys added by schema v2 (the back-compat reader fills them
#: with None for v1 records so a trainer sees one column set)
V2_FEATURE_KEYS = (
    "taint_density",
    "tainted_sinks",
    "sink_counts",
    "resolved_call_targets",
    "fingerprints",
    "static_answerable",
)

#: feature keys added by schema v4 (the cross-contract linker block;
#: same None-fill back-compat rule as V2_FEATURE_KEYS)
V4_FEATURE_KEYS = (
    "link_out_degree",
    "link_resolved_degree",
    "link_is_proxy",
    "link_proxy_kind",
    "link_delegatecall_sites",
    "link_escape_density",
)


class RoutingLog:
    """Thread-safe JSONL writer + bounded in-memory tail."""

    def __init__(self, capacity: int = 2048) -> None:
        self._mu = threading.Lock()
        self._tail: "deque[Dict]" = deque(maxlen=capacity)
        self.written = 0

    def record(
        self,
        contract: str,
        code_hash: str,
        features: Dict,
        outcome: Dict,
        journey_id: Optional[str] = None,
    ) -> Dict:
        from mythril_tpu import observe

        rec = {
            "schema_version": SCHEMA_VERSION,
            "contract": contract,
            "code_hash": code_hash,
            "features": features,
            "outcome": outcome,
            "journey_id": journey_id,
        }
        if not observe.enabled():
            return rec
        line = json.dumps(rec, sort_keys=True)
        out_dir = observe.out_dir()
        with self._mu:
            self._tail.append(rec)
            self.written += 1
            if out_dir:
                try:
                    with open(
                        os.path.join(out_dir, "routing_features.jsonl"), "a"
                    ) as fp:
                        fp.write(line + "\n")
                except OSError:
                    pass  # a full/readonly disk must not sink analysis
        return rec

    def tail(self, n: int = 256) -> List[Dict]:
        with self._mu:
            return list(self._tail)[-n:]

    def clear(self) -> None:
        with self._mu:
            self._tail.clear()


_LOG = RoutingLog()


def routing_log() -> RoutingLog:
    return _LOG


#: storage / call / env opcode sets for the density features (byte
#: scan over-approximates into PUSH data, uniformly for every
#: contract — fine for a ranking feature)
_STORAGE_OPS = (0x54, 0x55)  # SLOAD, SSTORE
_CALL_OPS = (0xF1, 0xF2, 0xF4, 0xFA)  # CALL family


def features_for(code_hex: str, summary=None, link=None) -> Dict:
    """The static feature vector for one contract. Uses the cached
    StaticSummary when available (CFG sizes, dead selectors, screened
    modules); degrades to byte-scan features when the static layer is
    off or failed — the record always exists. Pass ``summary=False``
    to skip the summary build outright (the microsecond admission
    tiers must not pay a CFG recovery for a telemetry row).

    ``link`` is an optional corpus-resolved link block
    (LinkSet.node_meta): when given it fills the schema-v4 features
    with graph-resolved values (resolved degree, escape density);
    without it the per-contract half (out-degree, proxy flags) still
    lands from the summary's own link node and the graph-level columns
    stay None."""
    code_hex = code_hex[2:] if code_hex.startswith("0x") else code_hex
    try:
        code = bytes.fromhex(code_hex)
    except ValueError:
        code = b""
    n = max(1, len(code))
    feats: Dict = {
        "code_bytes": len(code),
        "storage_op_density": round(
            sum(code.count(bytes([op])) for op in _STORAGE_OPS) / n, 5
        ),
        "call_op_density": round(
            sum(code.count(bytes([op])) for op in _CALL_OPS) / n, 5
        ),
    }
    if summary is False:
        summary = None
    elif summary is None:
        try:
            from mythril_tpu.analysis.static import (
                static_prune_enabled,
                summary_for,
            )

            if static_prune_enabled():
                summary = summary_for(code_hex)
        except Exception:
            summary = None
    if summary is not None:
        try:
            row = summary.lint_dict()
            feats.update(
                cfg_blocks=row.get("blocks"),
                cfg_reachable_blocks=row.get("reachable_blocks"),
                instructions=row.get("instructions"),
                selectors=row.get("selectors"),
                dead_selectors=row.get("dead_selectors"),
                dead_directions=row.get("dead_directions"),
                modules_screened=row.get("modules_applicable"),
                # schema v2: the taint/value-set block — how
                # attacker-steerable the contract is, how much of its
                # call/storage surface is constant, and whether the
                # triage tier can settle it outright (the single
                # strongest routing feature: cost zero)
                taint_density=(row.get("taint") or {}).get("density"),
                tainted_sinks=(
                    sum(
                        ((row.get("taint") or {}).get("tainted_sinks")
                         or {}).values()
                    )
                ),
                sink_counts=(row.get("taint") or {}).get("sinks"),
                resolved_call_targets=row.get(
                    "resolved_call_target_count"
                ),
                fingerprints=row.get("fingerprint_count"),
                static_answerable=row.get("static_answerable"),
            )
        except Exception:
            pass
    # schema v4: the cross-contract link block — corpus-resolved when
    # a LinkSet rode along, per-contract (graph columns None) otherwise
    link_row = link
    if link_row is None and summary is not None:
        node = getattr(summary, "link", None)
        if node is not None:
            try:
                link_row = node.as_dict()
            except Exception:
                link_row = None
    if link_row:
        feats.update(
            link_out_degree=link_row.get("out_degree"),
            link_resolved_degree=link_row.get("resolved_degree"),
            link_is_proxy=link_row.get("is_proxy"),
            link_proxy_kind=link_row.get("proxy_kind"),
            link_delegatecall_sites=link_row.get("delegatecall_sites"),
            link_escape_density=link_row.get("escape_density"),
        )
    try:
        from mythril_tpu.laser.batch import specialize as _spec

        phases = _spec.phases_for(
            _spec.signature_for(code, summary),
            fuse=_spec.fuse_profitable(code),
        )
        feats["phase_bucket_pruned"] = len(phases.pruned)
        feats["fuse_profitable"] = bool(phases.fuse_depth)
        # the FULL specialization bucket (not just its size): `myth
        # kernels bake --routing` mines these rows to prebake the
        # kernels live traffic actually dispatched (features are
        # open-ended — absent in old records reads as None)
        from mythril_tpu.compileplane.keys import bucket_key

        feats["phase_bucket"] = bucket_key(phases)
    except Exception:
        pass
    return feats


def outcome_for(result: Dict, prepass_stats: Optional[Dict] = None) -> Dict:
    """The outcome half of a routing record, from an analyze_corpus
    per-contract result dict (+ the corpus prepass stats when the
    device ran)."""
    if result.get("skipped"):
        route = "skipped"
    elif result.get("quarantined"):
        # a denylisted poison codehash settled FAILED at admission
        # (service quarantine) — blast-radius containment, zero
        # compute spent; the trainer must see these as their own class
        route = "quarantined"
    elif result.get("store_hit"):
        # settled at admission from the cross-run verdict store —
        # near-zero cost, the cache economics the item-5 cost model
        # must see (routes are open-ended; schema stays v2)
        route = "store-hit"
    elif result.get("static_answered"):
        route = "static-answer"
    elif result.get("store_incremental"):
        # fingerprint-diff re-analysis: only changed selectors paid
        # for compute, banked issues covered the rest
        route = "store-incremental"
    elif result.get("promoted"):
        # the cost-model router picked a tier, the tier overran its
        # predicted budget, and the job was promoted mid-flight — its
        # own outcome class so the trainer prices mis-routes
        route = "promoted-" + str(result["promoted"])
    elif result.get("routed"):
        # the cost-model router's own decision (routing/router.py):
        # recorded as routed-<tier> so the flywheel trains on its own
        # traffic (model.normalize_route folds it back onto <tier>)
        route = "routed-" + str(result["routed"])
    elif result.get("owned"):
        route = "device-owned"
    else:
        route = "host-walk"
    out: Dict = {
        "route": route,
        "wall_s": result.get("wall_s"),
        "issues": len(result.get("issues") or []),
        "states": result.get("states", 0),
        "complete": bool(result.get("complete", result.get("error") is None)),
        "error": bool(result.get("error")),
    }
    stats = prepass_stats or result.get("device_prepass") or {}
    if stats:
        out["waves"] = stats.get("waves", 0)
        out["device_sat"] = stats.get("device_sat", 0)
        out["host_sat"] = stats.get("host_sat", 0)
        out["device_steps"] = stats.get("device_steps", 0)
    return out


# ---------------------------------------------------------------------------
# the tail reader (trainer-side): version-tolerant JSONL parsing
# ---------------------------------------------------------------------------
def parse_record(line_or_obj) -> Dict:
    """One routing record from a JSONL line (or an already-decoded
    dict), normalized to the CURRENT schema: v1 records (no taint
    block) come back with every `V2_FEATURE_KEYS` column present and
    None — a trainer reads one column set across a mixed log. Raises
    ValueError on junk or a record from a FUTURE schema."""
    rec = (
        json.loads(line_or_obj)
        if isinstance(line_or_obj, (str, bytes))
        else dict(line_or_obj)
    )
    if not isinstance(rec, dict):
        raise ValueError("routing record is not an object")
    rec.setdefault("journey_id", None)  # pre-v3 records carry none
    missing = [k for k in RECORD_KEYS if k not in rec]
    if missing:
        raise ValueError(f"routing record missing keys: {missing}")
    version = int(rec["schema_version"])
    if version > SCHEMA_VERSION:
        raise ValueError(
            f"routing record schema v{version} is newer than this "
            f"reader (v{SCHEMA_VERSION})"
        )
    features = dict(rec.get("features") or {})
    for key in V2_FEATURE_KEYS + V4_FEATURE_KEYS:
        features.setdefault(key, None)
    rec["features"] = features
    return rec


def iter_records(path: str):
    """Stream parsed records off a routing JSONL file one line at a
    time — flywheel logs grow unboundedly under `myth watch`, and the
    trainer must not hold the raw text in memory to read them.
    Unparseable lines are skipped, not fatal — a half-written tail
    line must not sink the trainer."""
    with open(path) as fp:
        for line in fp:
            if not line.strip():
                continue
            try:
                yield parse_record(line)
            except ValueError:
                continue


def read_records(path: str, n: Optional[int] = None) -> List[Dict]:
    """The last `n` (default: all) records of a routing JSONL file,
    each normalized by `parse_record`. Streams the file (constant
    memory for the unbounded-`n` case is the caller's problem; with
    `n` the window is a bounded deque)."""
    if n is not None:
        return list(deque(iter_records(path), maxlen=n))
    return list(iter_records(path))


def tail_records(path: str, n: int) -> List[Dict]:
    """The last `n` records WITHOUT scanning the whole file: seek to
    the tail and read backwards in blocks until `n` parseable lines
    (plus one likely-partial head line) are in hand. `myth observe
    report` reads a multi-GB watch log's tail in milliseconds with
    this; `read_records(path, n)` is the always-correct slow path the
    block scan falls back to semantically (same result, pinned by the
    tests)."""
    if n <= 0:
        return []
    block = 64 * 1024
    with open(path, "rb") as fp:
        fp.seek(0, os.SEEK_END)
        end = fp.tell()
        chunks: List[bytes] = []
        pos = end
        # n+1 newlines guarantee n COMPLETE lines even when the scan
        # lands mid-line at the window head
        while pos > 0 and b"".join(chunks).count(b"\n") <= n:
            step = min(block, pos)
            pos -= step
            fp.seek(pos)
            chunks.insert(0, fp.read(step))
    buf = b"".join(chunks)
    lines = buf.split(b"\n")
    if pos > 0 and lines:
        lines = lines[1:]  # drop the partial line the window cut
    out: "deque[Dict]" = deque(maxlen=n)
    for raw in lines:
        line = raw.decode("utf-8", "replace").strip()
        if not line:
            continue
        try:
            out.append(parse_record(line))
        except ValueError:
            continue
    return list(out)
