"""Unified telemetry for the whole stack.

One subsystem, four surfaces (see docs/observability.md):

- **Metrics registry** (registry.py) — typed counters/gauges/
  histograms with labels; the single backing store the explorer, the
  service, the scheduler, the kernel cache and the phase profiler all
  register into. Exposed as Prometheus text at the service's
  ``/metrics``.
- **Structured spans** (spans.py) — ``trace(name, **attrs)`` nested
  spans in a bounded flight recorder, exportable as Chrome/Perfetto
  trace JSON (``--trace-out``, ``/trace``), auto-dumped on mesh/
  deadline degradations.
- **Solver query telemetry** (solverstats.py) — every SAT/SMT verdict
  tagged with its answering origin (host CDCL / device portfolio /
  memo), aggregated into the per-run attribution table the bench
  record and report meta carry.
- **Routing feature log** (routing.py) — one JSONL record per analyzed
  contract joining static features with route/outcome
  (``--observe-out DIR``): ROADMAP item 5's training set.

Global switches: `set_enabled(False)` (CLI ``--no-observe``) turns the
span/solver/routing recording into near-zero-cost no-ops — registry
arithmetic that backs *legacy* views (ExploreStats publication, /stats,
phase profile) stays on so product behavior never changes with
telemetry off. `configure(out_dir=...)` points file outputs (routing
JSONL, degradation flight dumps) at a directory.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from mythril_tpu.observe.querylog import (  # noqa: F401
    LOSS_REASONS,
    capture_enabled as query_capture_enabled,
    captured_total,
    configure_capture,
    loss_reasons,
    query_context,
    record_loss,
)
from mythril_tpu.observe.devicemon import (  # noqa: F401
    DeviceMonitor,
    device_monitor,
)
from mythril_tpu.observe.journey import (  # noqa: F401
    JourneyLog,
    assemble as assemble_journey,
    journey_event,
    journey_log,
    new_journey_id,
    tier_sequence,
)
from mythril_tpu.observe.registry import (  # noqa: F401 (public API)
    LATENCY_BUCKETS,
    SCHEMA_VERSION,
    SOLVER_WALL_BUCKETS,
    MetricsRegistry,
    registry,
    reset_registry,
)
from mythril_tpu.observe.slo import (  # noqa: F401
    HealthMonitor,
    Objective,
    SloEngine,
    default_objectives,
)
from mythril_tpu.observe.routing import (  # noqa: F401
    features_for as routing_features_for,
)
from mythril_tpu.observe.routing import outcome_for as routing_outcome_for  # noqa: F401,E501
from mythril_tpu.observe.routing import (  # noqa: F401
    parse_record as parse_routing_record,
)
from mythril_tpu.observe.routing import (  # noqa: F401
    read_records as read_routing_records,
)
from mythril_tpu.observe.routing import (  # noqa: F401
    tail_records as tail_routing_records,
)
from mythril_tpu.observe.routing import routing_log  # noqa: F401
from mythril_tpu.observe.solverstats import (  # noqa: F401
    ORIGIN_DEVICE,
    ORIGIN_HOST_CDCL,
    ORIGIN_MEMO,
    attribution as solver_attribution,
    marker as solver_marker,
    record_query,
)
from mythril_tpu.observe.spans import (  # noqa: F401
    FlightRecorder,
    export_trace,
    flight_recorder,
    overlap_fraction,
    to_perfetto,
    trace,
)

log = logging.getLogger(__name__)

_ENABLED = True
_OUT_DIR: Optional[str] = None
_DUMP_MU = threading.Lock()
_DUMPS = 0
#: bound on automatic degradation dumps per process: a degrading corpus
#: can log hundreds of events, and each dump serializes the whole ring
MAX_AUTO_DUMPS = 8


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """The --no-observe switch: gates span recording, solver query
    telemetry, routing records, and automatic flight dumps."""
    global _ENABLED
    _ENABLED = bool(on)


def out_dir() -> Optional[str]:
    return _OUT_DIR


def configure(out_dir: Optional[str] = None) -> None:
    """Point file outputs at `out_dir` (created if missing); None
    clears. Also arms the degradation auto-dump hook."""
    global _OUT_DIR
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    _OUT_DIR = out_dir or None
    _install_degradation_hook()


#: degradation reasons that dump the flight recorder: the two failure
#: classes where "what was in flight" is the question (a faulted mesh
#: group, a run that ran out of wall)
_DUMP_REASONS = ("mesh-group-degraded", "deadline-expired", "wave-abandoned")

_HOOKED = False


def _degradation_dump(reason: str, site: str) -> None:
    """resilience.DegradationLog hook: flush the flight recorder to
    the observe directory so the timeline that LED to the degradation
    survives the run."""
    global _DUMPS
    if not _ENABLED or _OUT_DIR is None or reason not in _DUMP_REASONS:
        return
    with _DUMP_MU:
        if _DUMPS >= MAX_AUTO_DUMPS:
            return
        _DUMPS += 1
        n = _DUMPS
    try:
        path = os.path.join(
            _OUT_DIR, f"flight-{reason}-{n}.trace.json"
        )
        export_trace(path)
        log.info("flight recorder dumped to %s (%s at %s)", path, reason, site)
    except Exception:
        log.debug("flight-recorder dump failed", exc_info=True)


def _install_degradation_hook() -> None:
    global _HOOKED
    if _HOOKED:
        return
    try:
        from mythril_tpu.support import resilience

        resilience.add_degradation_hook(_degradation_dump)
        _HOOKED = True
    except Exception:
        log.debug("degradation hook install failed", exc_info=True)


def auto_dump_count() -> int:
    return _DUMPS


def reset_auto_dumps() -> None:
    global _DUMPS
    with _DUMP_MU:
        _DUMPS = 0
