"""Per-job journey tracing: the tier-ladder timeline of one analysis
request.

Every job travels a ladder of tiers — admission, then either an
instant settle (verdict-store hit / static answer) or the full path
(queued -> lane grant -> device waves -> solver escalations -> host
walk -> settle). The flight recorder (spans.py) holds *spans*; this
module holds the sparse, per-job **tier-transition events** that turn
those spans into an answerable question: "what happened to job X, in
order, with timestamps".

- `journey_event(journey_id, tier, event, **attrs)` records one
  transition (a lock + dict append; honors the global observe
  switch).
- `assemble(journey_id)` builds the timeline document served at
  ``/v1/jobs/<id>/trace``: ordered events, the distinct tier
  sequence, per-tier dwell, and any flight-recorder spans tagged
  with this journey (``trace(..., job=<id>)``).
- The journey_id rides the routing JSONL (schema v3), so
  features ⨝ route ⨝ outcome ⨝ timeline joins offline.

The log is bounded (journeys evicted oldest-first past the capacity)
— it is an operational instrument, not an archive; long-term storage
is the routing JSONL + exported traces.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional

#: journey/timeline document schema version (pinned by the service
#: journey tests and docs/observability.md)
SCHEMA_VERSION = 1

#: the tier vocabulary, in ladder order (stable wire schema)
TIER_ADMISSION = "admission"
TIER_STORE_HIT = "store-hit"
TIER_STATIC_ANSWER = "static-answer"
TIER_QUEUED = "queued"
TIER_LANE_GRANT = "lane-grant"
TIER_WAVE = "wave"
TIER_SOLVER = "solver"
TIER_HOST_WALK = "host-walk"
TIER_SETTLE = "settle"
TIERS = (
    TIER_ADMISSION, TIER_STORE_HIT, TIER_STATIC_ANSWER, TIER_QUEUED,
    TIER_LANE_GRANT, TIER_WAVE, TIER_SOLVER, TIER_HOST_WALK, TIER_SETTLE,
)


def new_journey_id() -> str:
    """A journey id for paths with no natural job id (the corpus
    driver); service jobs reuse their job id so the trace endpoint
    needs no mapping."""
    return uuid.uuid4().hex[:16]


class JourneyLog:
    """Bounded process-wide map journey_id -> ordered event list."""

    def __init__(self, capacity: int = 1024) -> None:
        self._mu = threading.Lock()
        self._events: "OrderedDict[str, List[Dict]]" = OrderedDict()
        self.capacity = max(16, capacity)
        self.recorded = 0

    def event(
        self, journey_id: str, tier: str, event: str, **attrs
    ) -> None:
        from mythril_tpu import observe

        if not observe.enabled() or not journey_id:
            return
        row = {
            "t": round(time.perf_counter(), 6),
            "tier": tier,
            "event": event,
        }
        if attrs:
            row["attrs"] = {
                k: v for k, v in attrs.items() if v is not None
            }
        with self._mu:
            bucket = self._events.get(journey_id)
            if bucket is None:
                bucket = self._events[journey_id] = []
                while len(self._events) > self.capacity:
                    self._events.popitem(last=False)
            bucket.append(row)
            self.recorded += 1

    def events(self, journey_id: str) -> List[Dict]:
        with self._mu:
            return list(self._events.get(journey_id) or ())

    def known(self, journey_id: str) -> bool:
        with self._mu:
            return journey_id in self._events

    def clear(self) -> None:
        with self._mu:
            self._events.clear()

    def __len__(self) -> int:
        with self._mu:
            return len(self._events)


_LOG = JourneyLog()


def journey_log() -> JourneyLog:
    return _LOG


def journey_event(journey_id: str, tier: str, event: str, **attrs) -> None:
    """Record one tier transition on the process journey log."""
    _LOG.event(journey_id, tier, event, **attrs)


def tier_sequence(events: List[Dict]) -> List[str]:
    """The distinct tiers in first-touch order — the compact ladder
    fingerprint the tests pin ("admission, store-hit, settle" vs
    "admission, queued, lane-grant, wave, settle")."""
    seen: List[str] = []
    for row in events:
        tier = row.get("tier")
        if tier and (not seen or seen[-1] != tier) and tier not in seen:
            seen.append(tier)
    return seen


def assemble(
    journey_id: str, spans: Optional[List] = None
) -> Optional[Dict]:
    """The journey/timeline document for one id, or None when the id
    is unknown. `spans` defaults to the flight recorder's tail; spans
    whose attrs carry ``job == journey_id`` are attached (the host
    walk, per-job solver escalations)."""
    events = _LOG.events(journey_id)
    if not events:
        return None
    if spans is None:
        from mythril_tpu.observe.spans import flight_recorder

        spans = flight_recorder().tail(4096)
    t0 = events[0]["t"]
    t1 = events[-1]["t"]
    tiers = tier_sequence(events)
    # per-tier dwell: time from a tier's first event to the next
    # tier's first event (the last tier dwells to the final event)
    first_touch: Dict[str, float] = {}
    for row in events:
        first_touch.setdefault(row["tier"], row["t"])
    dwell: Dict[str, float] = {}
    for i, tier in enumerate(tiers):
        end = (
            first_touch[tiers[i + 1]] if i + 1 < len(tiers) else t1
        )
        dwell[tier] = round(max(0.0, end - first_touch[tier]), 6)
    doc = {
        "schema_version": SCHEMA_VERSION,
        "journey_id": journey_id,
        "tiers": tiers,
        "tier_dwell_s": dwell,
        "events": events,
        "wall_s": round(t1 - t0, 6),
    }
    attached = [
        span.as_dict()
        for span in spans
        if span.attrs and span.attrs.get("job") == journey_id
    ]
    if attached:
        doc["spans"] = attached
    return doc
