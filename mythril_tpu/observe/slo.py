"""The tier-ladder SLO engine: declarative latency/availability
objectives evaluated over registry histograms, multi-window burn-rate
accounting, and the health state machine a fleet front routes on.

ROADMAP item 2 (federated serving) wants "draining/red-lining driven
by each replica's Prometheus /metrics" — which presumes a replica can
score its own health. PR 7 built the raw series; this module is the
control plane on top, the same pattern production inference stacks
use:

- An **Objective** declares an error budget over registry series:
  either a *latency* objective (fraction of histogram observations
  above a threshold must stay under the budget — "p95 settle < 5s" is
  budget 0.05 at threshold 5s) or a *ratio* objective (bad-event
  counter over total-event counter must stay under the budget).
- The **SloEngine** samples the registry on a clock, keeps a bounded
  ring of timestamped snapshots, and evaluates every objective over a
  SHORT and a LONG window. The **burn rate** is bad-fraction /
  budget: 1.0 means the budget is being spent exactly as fast as
  allowed; 10x means the budget dies in a tenth of the window.
  Multi-window gating (both windows burning) is the standard
  flap-damper: a one-sample spike trips the short window but not the
  long one.
- The **HealthMonitor** folds the objective states with lifecycle
  facts (arena warming, background kernel compiles, draining) into
  one machine — ``ok -> degraded -> redlined`` — exported as the
  ``mtpu_health_state`` gauge plus per-objective
  ``mtpu_health_burn_rate{objective=,window=}`` gauges, and into the
  reasoned readiness split `/healthz` serves: *liveness* ("the
  process answers") vs *readiness* ("route new work here").

Redline/not-ready reasons are an enumerated, stable vocabulary
(`REDLINE_REASONS`, `NOT_READY_REASONS`): the future federation front
switches on them, so they are wire schema, not log strings.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from mythril_tpu.observe.registry import MetricsRegistry, _label_key, registry

#: health states in severity order; the gauge value is the index
STATE_OK = "ok"
STATE_DEGRADED = "degraded"
STATE_REDLINED = "redlined"
HEALTH_STATES = (STATE_OK, STATE_DEGRADED, STATE_REDLINED)

#: the enumerated redline vocabulary (stable wire schema — the
#: federation front switches on these)
REDLINE_SLO_BURN = "slo-burn"
REDLINE_QUEUE_SATURATED = "queue-saturated"
REDLINE_DEVICE_SATURATED = "device-saturated"
#: prefix form `breaker-open:<tier>`: a tier circuit breaker
#: (support/breaker.py) is OPEN and the replica is serving through
#: its fallback ladder — the federation front should route around it
#: until the breaker's half-open probe recovers
REDLINE_BREAKER_OPEN = "breaker-open"
#: fleet-front vocabulary (fleet/front.py): prefix form
#: `replica-lost:<name>` — a replica's death breaker tripped open
#: (probe timeouts / connection-refused streak) and its in-flight
#: jobs were failed over to survivors; `fleet-degraded` — at least
#: one replica is unroutable but the fleet still has ready capacity;
#: `fleet-saturated` — NO replica is accepting work and the front is
#: shedding submissions with Retry-After
REDLINE_REPLICA_LOST = "replica-lost"
REDLINE_FLEET_DEGRADED = "fleet-degraded"
REDLINE_FLEET_SATURATED = "fleet-saturated"
#: chainstream vocabulary (chainstream/watcher.py): `rpc-endpoints-
#: down` — every configured execution-client endpoint's death breaker
#: is open and the head stream is stalled; `head-lag` — the cursor
#: has fallen more than the configured block budget behind the quorum
#: chain head; `backfill-saturated` — the gap between cursor and head
#: exceeds the backfill window (alerting latency can no longer meet
#: the block-time SLO until the backlog drains)
REDLINE_RPC_ENDPOINTS_DOWN = "rpc-endpoints-down"
REDLINE_HEAD_LAG = "head-lag"
REDLINE_BACKFILL_SATURATED = "backfill-saturated"
REDLINE_REASONS = (
    REDLINE_SLO_BURN,
    REDLINE_QUEUE_SATURATED,
    REDLINE_DEVICE_SATURATED,
    REDLINE_BREAKER_OPEN,
    REDLINE_REPLICA_LOST,
    REDLINE_FLEET_DEGRADED,
    REDLINE_FLEET_SATURATED,
    REDLINE_RPC_ENDPOINTS_DOWN,
    REDLINE_HEAD_LAG,
    REDLINE_BACKFILL_SATURATED,
)

#: the enumerated not-ready vocabulary for the readiness half of
#: /healthz (liveness stays true through all of these)
NOT_READY_WARMING = "arena-warming"
NOT_READY_KERNEL_WARMUP = "kernel-warmup"
NOT_READY_DRAINING = "draining"
NOT_READY_REDLINED = "redlined"
NOT_READY_REASONS = (
    NOT_READY_WARMING,
    NOT_READY_KERNEL_WARMUP,
    NOT_READY_DRAINING,
    NOT_READY_REDLINED,
)


class Objective:
    """One declarative service-level objective over registry series.

    kind="latency": `metric` names a histogram; an observation above
    `threshold_s` is a bad event; the bad fraction must stay under
    `budget` (0.05 = "p95 under the threshold").

    kind="ratio": `numerator` (name, label-filter) counts bad events,
    `denominator` counts all events; bad/total must stay under
    `budget`. A label filter of {} sums every series of the family;
    given labels must match exactly (extra labels on the series are
    ignored).
    """

    def __init__(
        self,
        name: str,
        kind: str,
        budget: float,
        description: str = "",
        metric: Optional[str] = None,
        threshold_s: Optional[float] = None,
        numerator: Optional[Tuple[str, Dict[str, str]]] = None,
        denominator: Optional[Tuple[str, Dict[str, str]]] = None,
        min_events: int = 1,
    ) -> None:
        if kind not in ("latency", "ratio"):
            raise ValueError(f"unknown objective kind {kind!r}")
        if kind == "latency" and (metric is None or threshold_s is None):
            raise ValueError("latency objective wants metric + threshold_s")
        if kind == "ratio" and (numerator is None or denominator is None):
            raise ValueError("ratio objective wants numerator + denominator")
        self.name = name
        self.kind = kind
        self.budget = float(budget)
        self.description = description
        self.metric = metric
        self.threshold_s = threshold_s
        self.numerator = numerator
        self.denominator = denominator
        #: windows with fewer total events than this report burn 0 —
        #: an idle replica is healthy, not divide-by-zero degraded
        self.min_events = min_events

    def as_dict(self) -> Dict:
        out = {
            "name": self.name,
            "kind": self.kind,
            "budget": self.budget,
            "description": self.description,
        }
        if self.kind == "latency":
            out["metric"] = self.metric
            out["threshold_s"] = self.threshold_s
        else:
            out["numerator"] = [self.numerator[0], dict(self.numerator[1])]
            out["denominator"] = [
                self.denominator[0], dict(self.denominator[1]),
            ]
        return out


def default_objectives() -> List[Objective]:
    """The service's shipped objective set (docs/observability.md has
    the schema table). Budgets are serving-shaped defaults; embedders
    pass their own list to HealthMonitor."""
    return [
        Objective(
            name="warm-settle-p95",
            kind="latency",
            metric="mtpu_service_job_latency_seconds",
            threshold_s=10.0,
            budget=0.05,
            description="95% of jobs settle within 10s",
            min_events=4,
        ),
        Objective(
            name="admission-availability",
            kind="ratio",
            numerator=("mtpu_service_admissions_total",
                       {"outcome": "rejected-full"}),
            denominator=("mtpu_service_admissions_total", {}),
            budget=0.05,
            description="under 5% of submissions refused on backpressure",
            min_events=4,
        ),
        Objective(
            name="wave-abandon",
            kind="ratio",
            numerator=("mtpu_degradations_total",
                       {"reason": "wave-abandoned"}),
            denominator=("mtpu_service_waves_total", {}),
            budget=0.02,
            description="under 2% of waves die past the resilience ladder",
            min_events=2,
        ),
        Objective(
            name="solver-escalation-share",
            kind="ratio",
            numerator=("mtpu_solver_escalations_total", {}),
            denominator=("mtpu_solver_queries_total", {}),
            budget=0.5,
            description=(
                "under half of solver queries climb past the first "
                "ladder rung"
            ),
            min_events=16,
        ),
    ]


def _sum_family(snap: Dict, name: str, labels: Dict[str, str]) -> float:
    """Sum every series of `name` whose label set CONTAINS `labels`."""
    want = set(_label_key(labels))
    total = 0.0
    for key, value in (snap.get(name) or {}).items():
        if isinstance(value, dict):
            value = value.get("count", 0)
        if want <= set(key):
            total += float(value)
    return total


def _hist_family(snap: Dict, name: str) -> Tuple[List[int], int]:
    """Element-wise summed bucket counts + total count over every
    series of histogram `name` in one snapshot."""
    buckets: List[int] = []
    count = 0
    for value in (snap.get(name) or {}).values():
        if not isinstance(value, dict):
            continue
        row = value.get("buckets") or []
        if len(row) > len(buckets):
            buckets.extend([0] * (len(row) - len(buckets)))
        for i, n in enumerate(row):
            buckets[i] += n
        count += int(value.get("count", 0))
    return buckets, count


def quantile_from_buckets(
    bounds: Sequence[float], counts: Sequence[int], q: float
) -> Optional[float]:
    """Prometheus-style linear interpolation of quantile `q` from
    cumulative-izable bucket counts (`counts` has len(bounds)+1, the
    last being the overflow). None when empty."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0
    lo = 0.0
    for bound, n in zip(bounds, counts):
        if cum + n >= rank and n > 0:
            return lo + (bound - lo) * (rank - cum) / n
        cum += n
        lo = bound
    return float(bounds[-1]) if bounds else None


class ObjectiveStatus:
    """One objective's evaluation: per-window burn rates + the state
    the multi-window gate assigns."""

    __slots__ = ("objective", "burn_short", "burn_long", "state",
                 "bad", "total", "p95")

    def __init__(self, objective, burn_short, burn_long, state,
                 bad, total, p95=None) -> None:
        self.objective = objective
        self.burn_short = burn_short
        self.burn_long = burn_long
        self.state = state
        self.bad = bad
        self.total = total
        self.p95 = p95

    def as_dict(self) -> Dict:
        out = {
            "objective": self.objective.name,
            "state": self.state,
            "burn_short": round(self.burn_short, 3),
            "burn_long": round(self.burn_long, 3),
            "bad": self.bad,
            "total": self.total,
            "budget": self.objective.budget,
        }
        if self.p95 is not None:
            out["p95_s"] = round(self.p95, 6)
        return out


class SloEngine:
    """Registry sampler + objective evaluator.

    `sample()` snapshots the registry, appends to the bounded
    snapshot ring, and evaluates every objective over the short and
    long windows (delta between the newest snapshot and the oldest
    one inside each window). Degraded needs BOTH windows burning
    (>= 1.0); redlined needs both windows past `redline_burn`.
    """

    def __init__(
        self,
        objectives: Optional[List[Objective]] = None,
        short_window_s: float = 60.0,
        long_window_s: float = 600.0,
        redline_burn: float = 10.0,
        reg: Optional[MetricsRegistry] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.objectives = (
            list(objectives) if objectives is not None
            else default_objectives()
        )
        self.short_window_s = short_window_s
        self.long_window_s = long_window_s
        self.redline_burn = redline_burn
        self._reg = reg
        self._clock = clock
        self._mu = threading.Lock()
        # enough samples to cover the long window at a 1s cadence
        self._ring: "deque[Tuple[float, Dict]]" = deque(maxlen=1024)
        self._last: List[ObjectiveStatus] = []
        self._start_t = self._clock()

    @property
    def reg(self) -> MetricsRegistry:
        return self._reg if self._reg is not None else registry()

    def _window_delta(self, now_t, now_snap, window_s):
        """(old_snap, span_s): the baseline is the oldest sample
        inside `window_s`; with only out-of-window history, the
        newest predecessor (the closest boundary). The very FIRST
        sample has no window at all — old_snap is None and the
        evaluation reports zero burn rather than scoring the entire
        process history (which in an embedding process may predate
        this engine entirely) as one window."""
        oldest: Optional[Tuple[float, Dict]] = None
        for t, snap in self._ring:
            if now_t - t <= window_s:
                oldest = (t, snap)
                break
        if oldest is None:
            if not self._ring:
                return None, max(1e-9, now_t - self._start_t)
            oldest = self._ring[-1]
        return oldest[1], max(1e-9, now_t - oldest[0])

    def _evaluate_one(self, objective, now_snap, old_short, old_long):
        def bad_total(old_snap):
            if objective.kind == "ratio":
                num_name, num_labels = objective.numerator
                den_name, den_labels = objective.denominator
                bad = _sum_family(now_snap, num_name, num_labels) - \
                    _sum_family(old_snap, num_name, num_labels)
                total = _sum_family(now_snap, den_name, den_labels) - \
                    _sum_family(old_snap, den_name, den_labels)
                return max(0.0, bad), max(0.0, total), None
            bounds = self.reg.buckets_of(objective.metric)
            now_b, now_n = _hist_family(now_snap, objective.metric)
            old_b, old_n = _hist_family(old_snap, objective.metric)
            counts = [
                a - (old_b[i] if i < len(old_b) else 0)
                for i, a in enumerate(now_b)
            ]
            total = now_n - old_n
            bad = 0.0
            for i, bound in enumerate(bounds):
                if bound > objective.threshold_s and i < len(counts):
                    bad += counts[i]
            if len(counts) > len(bounds):
                bad += counts[-1]  # the overflow bucket
            p95 = quantile_from_buckets(bounds, counts, 0.95)
            return max(0.0, bad), max(0.0, float(total)), p95

        def burn(old_snap):
            if old_snap is None:  # the first sample: no window yet
                return 0.0, 0.0, 0.0, None
            bad, total, p95 = bad_total(old_snap)
            if total < objective.min_events:
                return 0.0, bad, total, p95
            fraction = bad / total if total else 0.0
            return fraction / objective.budget, bad, total, p95

        burn_short, bad, total, p95 = burn(old_short)
        burn_long, _bad_l, _total_l, _ = burn(old_long)
        if (
            burn_short >= self.redline_burn
            and burn_long >= self.redline_burn
        ):
            state = STATE_REDLINED
        elif burn_short >= 1.0 and burn_long >= 1.0:
            state = STATE_DEGRADED
        else:
            state = STATE_OK
        return ObjectiveStatus(
            objective, burn_short, burn_long, state, bad, total, p95
        )

    def sample(self) -> List[ObjectiveStatus]:
        now_t = self._clock()
        now_snap = self.reg.snapshot()
        with self._mu:
            old_short, _ = self._window_delta(
                now_t, now_snap, self.short_window_s
            )
            old_long, _ = self._window_delta(
                now_t, now_snap, self.long_window_s
            )
            statuses = [
                self._evaluate_one(o, now_snap, old_short, old_long)
                for o in self.objectives
            ]
            self._ring.append((now_t, now_snap))
            self._last = statuses
        burn_gauge = self.reg.gauge(
            "mtpu_health_burn_rate",
            "SLO error-budget burn rate by objective and window "
            "(1.0 = budget spent exactly at the allowed rate)",
        )
        for status in statuses:
            burn_gauge.labels(
                objective=status.objective.name, window="short"
            ).set(status.burn_short)
            burn_gauge.labels(
                objective=status.objective.name, window="long"
            ).set(status.burn_long)
        return statuses

    def statuses(self) -> List[ObjectiveStatus]:
        with self._mu:
            return list(self._last)


class HealthMonitor:
    """The replica's health state machine.

    Folds the SLO engine's objective states with lifecycle facts the
    embedder injects as callables:

    - `warming_fn`    True while the arena warmup compile is in flight
    - `compiling_fn`  True while background kernel warmups are running
    - `draining_fn`   True once the drain began
    - `saturation_fn` optional extra redline reasons (queue/device
                      saturation) -> list of REDLINE_REASONS entries

    `state()`: ok | degraded | redlined from the worst objective plus
    saturation reasons. `ready()`: route-new-work-here — False while
    warming, compiling, draining, or redlined, each with its
    enumerated reason. Exports `mtpu_health_state` / `mtpu_health_ready`
    gauges on every sample.
    """

    def __init__(
        self,
        slo: Optional[SloEngine] = None,
        warming_fn: Optional[Callable[[], bool]] = None,
        compiling_fn: Optional[Callable[[], bool]] = None,
        draining_fn: Optional[Callable[[], bool]] = None,
        saturation_fn: Optional[Callable[[], List[str]]] = None,
        reg: Optional[MetricsRegistry] = None,
    ) -> None:
        self.slo = slo if slo is not None else SloEngine(reg=reg)
        self._warming = warming_fn or (lambda: False)
        self._compiling = compiling_fn or (lambda: False)
        self._draining = draining_fn or (lambda: False)
        self._saturation = saturation_fn or (lambda: [])
        self._reg = reg
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @property
    def reg(self) -> MetricsRegistry:
        return self._reg if self._reg is not None else registry()

    # -- evaluation ----------------------------------------------------
    def sample(self) -> Dict:
        """One sampler tick: evaluate the objectives, fold the state,
        export the gauges, return the healthz payload."""
        self.slo.sample()
        payload = self.healthz_payload()
        self.reg.gauge(
            "mtpu_health_state",
            "replica health state (0=ok, 1=degraded, 2=redlined)",
        ).set(HEALTH_STATES.index(payload["state"]))
        self.reg.gauge(
            "mtpu_health_ready",
            "replica readiness (1 = route new work here)",
        ).set(1.0 if payload["ready"] else 0.0)
        return payload

    def state(self) -> Tuple[str, List[str]]:
        """(state, redline/degrade reasons) from the last evaluation
        plus live saturation facts."""
        reasons: List[str] = []
        worst = STATE_OK
        for status in self.slo.statuses():
            if status.state == STATE_REDLINED:
                worst = STATE_REDLINED
                reasons.append(
                    f"{REDLINE_SLO_BURN}:{status.objective.name}"
                )
            elif status.state == STATE_DEGRADED:
                if worst == STATE_OK:
                    worst = STATE_DEGRADED
                reasons.append(f"slo-degraded:{status.objective.name}")
        for reason in self._saturation():
            worst = STATE_REDLINED
            reasons.append(reason)
        return worst, reasons

    def ready(self) -> Tuple[bool, List[str]]:
        reasons: List[str] = []
        if self._draining():
            reasons.append(NOT_READY_DRAINING)
        if self._warming():
            reasons.append(NOT_READY_WARMING)
        if self._compiling():
            reasons.append(NOT_READY_KERNEL_WARMUP)
        state, _ = self.state()
        if state == STATE_REDLINED:
            reasons.append(NOT_READY_REDLINED)
        return not reasons, reasons

    def healthz_payload(self) -> Dict:
        """The upgraded /healthz body: liveness ("ok", always true
        when this code runs), the health state + reasons, and the
        readiness split with its enumerated reasons."""
        state, state_reasons = self.state()
        ready, ready_reasons = self.ready()
        return {
            "ok": True,  # liveness: the process answered
            "state": state,
            "reasons": state_reasons,
            "ready": ready,
            "not_ready_reasons": ready_reasons,
            "objectives": [
                s.as_dict() for s in self.slo.statuses()
            ],
        }

    # -- the sampler thread --------------------------------------------
    def start(self, interval_s: float = 2.0) -> "HealthMonitor":
        if self._thread is None:
            self._stop.clear()

            def _loop():
                while not self._stop.wait(interval_s):
                    try:
                        self.sample()
                    except Exception:  # telemetry never sinks serving
                        pass

            self._thread = threading.Thread(
                target=_loop, name="myth-health-sampler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
