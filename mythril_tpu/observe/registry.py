"""The process-wide metrics registry: typed counters, gauges and
histograms with label sets.

Every layer of the stack used to keep its own ad-hoc counters —
`ExploreStats` ints, hand-assembled `/stats` dicts in the service
engine, the `phase_profile` wall-clock singleton — none sharing a
schema or a consistency boundary. This registry is the single backing
store they register into:

- **Counters** — monotone floats; `inc(n)`. The explorer publishes its
  per-run `ExploreStats` here (``mtpu_explore_*``), the solver stack
  its per-origin query attribution (``mtpu_solver_*``), the service
  its wave/pipeline/kernel series (``mtpu_service_*``).
- **Gauges** — last-writer-wins floats (`set`) plus `set_max` for
  high-water marks.
- **Histograms** — fixed log-spaced buckets, per-label `sum`/`count`;
  `support/phase_profile.py` is a delta view over these.
- **Snapshot** — `snapshot()` returns every series under ONE lock
  acquisition, so a reader (the service `/stats` assembly) sees a
  point-in-time-consistent view instead of field-by-field reads racing
  the wave loop. `marker()`/`since(marker)` give per-run deltas on the
  same snapshot machinery.
- **Exposition** — `prometheus_text()` renders the whole registry in
  the Prometheus text format (0.0.4): the service serves it at
  ``/metrics``.

Metric mutation is a dict update under one process lock: cheap enough
for every call site in this codebase (the hot device loop never
touches the registry — instrumentation lives at wave/query/contract
granularity). The spans/solver/routing layers additionally honor the
global enable switch (`mythril_tpu.observe.set_enabled`); registry
arithmetic itself stays on so legacy views (ExploreStats, /stats,
phase profile) never change behavior with telemetry off.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple

#: bumped when the snapshot/exposition schema changes shape; surfaced
#: in /stats, /trace, and the routing JSONL so smoke tools can pin it
SCHEMA_VERSION = 1

#: default histogram buckets (seconds-ish log spacing; callers with a
#: different unit pass their own)
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0, 300.0,
)

#: job-latency ladder: the settle spectrum spans ~1.9ms verdict-store
#: hits to ~21s cold host walks (BENCH_r06), so the warm tiers need
#: sub-5ms resolution the default ladder crushes into one bucket
LATENCY_BUCKETS = (
    0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

#: solver-wall ladder: memo hits are microseconds, CDCL marathons tens
#: of seconds — two extra decades below the default ladder's floor
SOLVER_WALL_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
    1.0, 5.0, 10.0, 30.0,
)

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _render_labels(key: Tuple[Tuple[str, str], ...], extra=()) -> str:
    pairs = list(key) + list(extra)
    if not pairs:
        return ""
    body = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in pairs
    )
    return "{" + body + "}"


class _Child:
    """One (metric, label set) series. Handles are cached on the
    parent, so hot call sites resolve labels once and keep the
    handle."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "Metric", key) -> None:
        self._metric = metric
        self._key = key

    # counters / gauges
    def inc(self, n: float = 1.0) -> None:
        self._metric._inc(self._key, n)

    def set(self, value: float) -> None:
        self._metric._set(self._key, value)

    def set_max(self, value: float) -> None:
        self._metric._set_max(self._key, value)

    @property
    def value(self) -> float:
        return self._metric._value(self._key)

    # histograms
    def observe(self, value: float) -> None:
        self._metric._observe(self._key, value)

    def add_raw(self, sum_delta: float, count_delta: int) -> None:
        """Fold pre-aggregated wall into the histogram (sum/count move,
        bucket counts take one observation of the mean) — the
        phase-profile `add(phase, seconds, n)` path."""
        self._metric._add_raw(self._key, sum_delta, count_delta)

    @property
    def sum(self) -> float:
        return self._metric._hist_sum(self._key)

    @property
    def count(self) -> int:
        return self._metric._hist_count(self._key)


class Metric:
    """One named family; all state guarded by the registry lock."""

    def __init__(
        self,
        name: str,
        kind: str,
        help_text: str,
        lock: threading.RLock,
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> None:
        self.name = name
        self.kind = kind
        self.help = help_text
        self._lock = lock
        self.buckets = tuple(buckets)
        #: label key -> float (counter/gauge) or [bucket_counts, sum,
        #: count] (histogram)
        self._series: Dict = {}
        self._children: Dict = {}

    def labels(self, **labels) -> _Child:
        key = _label_key(labels)
        child = self._children.get(key)
        if child is None:
            child = self._children[key] = _Child(self, key)
        return child

    # default (label-less) conveniences
    def inc(self, n: float = 1.0) -> None:
        self._inc((), n)

    def set(self, value: float) -> None:
        self._set((), value)

    def set_max(self, value: float) -> None:
        self._set_max((), value)

    def observe(self, value: float) -> None:
        self._observe((), value)

    @property
    def value(self) -> float:
        return self._value(())

    # -- guarded primitives -------------------------------------------
    def _inc(self, key, n: float) -> None:
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + n

    def _set(self, key, value: float) -> None:
        with self._lock:
            self._series[key] = float(value)

    def _set_max(self, key, value: float) -> None:
        with self._lock:
            self._series[key] = max(self._series.get(key, 0.0), float(value))

    def _value(self, key) -> float:
        with self._lock:
            if self.kind == HISTOGRAM:
                row = self._series.get(key)
                return row[1] if row else 0.0
            return self._series.get(key, 0.0)

    def _hist_row(self, key):
        row = self._series.get(key)
        if row is None:
            row = self._series[key] = [[0] * (len(self.buckets) + 1), 0.0, 0]
        return row

    def _observe(self, key, value: float) -> None:
        with self._lock:
            row = self._hist_row(key)
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    row[0][i] += 1
                    break
            else:
                row[0][-1] += 1
            row[1] += value
            row[2] += 1

    def _add_raw(self, key, sum_delta: float, count_delta: int) -> None:
        with self._lock:
            row = self._hist_row(key)
            mean = sum_delta / count_delta if count_delta else 0.0
            for i, bound in enumerate(self.buckets):
                if mean <= bound:
                    row[0][i] += count_delta
                    break
            else:
                row[0][-1] += count_delta
            row[1] += sum_delta
            row[2] += count_delta

    def _hist_sum(self, key) -> float:
        with self._lock:
            row = self._series.get(key)
            return row[1] if row else 0.0

    def _hist_count(self, key) -> int:
        with self._lock:
            row = self._series.get(key)
            return row[2] if row else 0


class MetricsRegistry:
    """Name -> Metric, with one lock for every mutation and snapshot.

    `collector(fn)` registers a scrape-time callback yielding
    ``(name, labels_dict, value)`` gauge samples — the bridge for
    state that lives behind another object's lock (queue depth, cache
    size) without double bookkeeping."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._metrics: "Dict[str, Metric]" = {}
        self._collectors: List[Callable] = []

    # -- registration --------------------------------------------------
    def _metric(self, name, kind, help_text, buckets=DEFAULT_BUCKETS):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = self._metrics[name] = Metric(
                    name, kind, help_text, self._lock, buckets
                )
            elif metric.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {metric.kind}"
                )
            elif (
                kind == HISTOGRAM
                and tuple(buckets) != metric.buckets
                and tuple(buckets) != DEFAULT_BUCKETS
            ):
                # per-metric bucket override on re-registration: adopt
                # the explicit ladder while the series is still empty
                # (bucket counts would be meaningless across a switch);
                # once observations exist the first ladder wins
                if not metric._series:
                    metric.buckets = tuple(buckets)
            return metric

    def counter(self, name: str, help_text: str = "") -> Metric:
        return self._metric(name, COUNTER, help_text)

    def gauge(self, name: str, help_text: str = "") -> Metric:
        return self._metric(name, GAUGE, help_text)

    def histogram(
        self, name: str, help_text: str = "",
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> Metric:
        return self._metric(name, HISTOGRAM, help_text, buckets)

    def collector(self, fn: Callable) -> None:
        with self._lock:
            self._collectors.append(fn)

    # -- reading -------------------------------------------------------
    def snapshot(self) -> Dict[str, Dict]:
        """Every series, read under ONE lock acquisition: a consistent
        point-in-time view for /stats assembly and delta markers.
        Histograms snapshot as {"sum": s, "count": n, "buckets":
        [...]}; counters/gauges as floats. Collector samples are
        merged in afterwards (they guard their own state)."""
        with self._lock:
            out: Dict[str, Dict] = {}
            for name, metric in self._metrics.items():
                series = {}
                for key, value in metric._series.items():
                    if metric.kind == HISTOGRAM:
                        series[key] = {
                            "sum": value[1],
                            "count": value[2],
                            "buckets": list(value[0]),
                        }
                    else:
                        series[key] = value
                out[name] = series
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                for name, labels, value in fn():
                    out.setdefault(name, {})[_label_key(labels)] = value
            except Exception:  # a broken collector must not sink /stats
                pass
        return out

    def buckets_of(self, name: str) -> Tuple[float, ...]:
        """A histogram's bucket bounds (DEFAULT_BUCKETS for unknown
        names) — snapshot consumers (the SLO engine) pair these with
        the snapshot's bucket counts."""
        with self._lock:
            metric = self._metrics.get(name)
            return metric.buckets if metric is not None else DEFAULT_BUCKETS

    def value(self, name: str, **labels) -> float:
        metric = self._metrics.get(name)
        if metric is None:
            return 0.0
        return metric._value(_label_key(labels))

    def marker(self) -> Dict:
        """Snapshot for delta accounting (per-run attribution over
        process-cumulative series)."""
        return self.snapshot()

    def since(self, marker: Dict) -> Dict[str, Dict]:
        """Counter/histogram deltas since `marker` (gauges report the
        current value — a high-water mark has no meaningful delta)."""
        now = self.snapshot()
        out: Dict[str, Dict] = {}
        for name, series in now.items():
            metric = self._metrics.get(name)
            base = marker.get(name, {})
            for key, value in series.items():
                if isinstance(value, dict):  # histogram
                    prev = base.get(key, {"sum": 0.0, "count": 0})
                    delta = {
                        "sum": value["sum"] - prev.get("sum", 0.0),
                        "count": value["count"] - prev.get("count", 0),
                    }
                    if delta["count"] or delta["sum"]:
                        out.setdefault(name, {})[key] = delta
                elif metric is not None and metric.kind == GAUGE:
                    out.setdefault(name, {})[key] = value
                else:
                    delta = value - base.get(key, 0.0)
                    if delta:
                        out.setdefault(name, {})[key] = delta
        return out

    # -- exposition ----------------------------------------------------
    def prometheus_text(self) -> str:
        """The whole registry in the Prometheus text exposition format
        (0.0.4): HELP/TYPE headers, label-sorted series, histogram
        cumulative buckets + _sum/_count."""
        snap = self.snapshot()
        with self._lock:
            kinds = {n: m.kind for n, m in self._metrics.items()}
            helps = {n: m.help for n, m in self._metrics.items()}
            bucket_bounds = {
                n: m.buckets
                for n, m in self._metrics.items()
                if m.kind == HISTOGRAM
            }
        lines: List[str] = []
        for name in sorted(snap):
            kind = kinds.get(name, GAUGE)
            if helps.get(name):
                lines.append(f"# HELP {name} {helps[name]}")
            lines.append(f"# TYPE {name} {kind}")
            series = snap[name]
            for key in sorted(series):
                value = series[key]
                if isinstance(value, dict):  # histogram
                    bounds = bucket_bounds.get(name, DEFAULT_BUCKETS)
                    cum = 0
                    for bound, n in zip(bounds, value["buckets"]):
                        cum += n
                        lines.append(
                            f"{name}_bucket"
                            f"{_render_labels(key, (('le', _fmt(bound)),))}"
                            f" {cum}"
                        )
                    cum += value["buckets"][-1]
                    lines.append(
                        f"{name}_bucket"
                        f"{_render_labels(key, (('le', '+Inf'),))} {cum}"
                    )
                    lines.append(
                        f"{name}_sum{_render_labels(key)} {_fmt(value['sum'])}"
                    )
                    lines.append(
                        f"{name}_count{_render_labels(key)} {value['count']}"
                    )
                else:
                    lines.append(
                        f"{name}{_render_labels(key)} {_fmt(value)}"
                    )
        return "\n".join(lines) + "\n"


def _fmt(value: float) -> str:
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return repr(value) if isinstance(value, float) else str(value)


_REGISTRY: Optional[MetricsRegistry] = None
_REGISTRY_LOCK = threading.Lock()


def registry() -> MetricsRegistry:
    """The process-wide registry (lazily created; tests may swap it
    with `reset_registry` for isolation)."""
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = MetricsRegistry()
    return _REGISTRY


def reset_registry() -> MetricsRegistry:
    """Replace the process registry with a fresh one (test isolation).
    Handles held by long-lived objects keep writing to the OLD
    registry; production code never calls this."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        _REGISTRY = MetricsRegistry()
    return _REGISTRY
