"""Solver query telemetry: per-origin attribution of every SAT/SMT
verdict.

ROADMAP item 1 ("make the on-device solver actually win") is blocked
on exactly one question the old counters could not answer: *which
engine answered which query, at what cost, after how many escalation
hops*. `SolverStatistics` keeps two global sat counts; this module
tags every query with its **origin** and **verdict** and aggregates
them into the attribution table that lands in the bench record
(`solver_attribution`) and the jsonv2 report meta.

Origins, in escalation-ladder order:

    memo              the get_model verdict cache pre-empted the solve
    host-cdcl         native CDCL (sprint or marathon)
    device-portfolio  the on-chip portfolio (flip batches, race wins,
                      the --parallel-solving escape hatch) — hop >= 1
    host-z3           reserved: an external-solver escalation rung
                      (not wired in this build; the label is part of
                      the stable schema so downstream dashboards don't
                      churn when it lands)

Backing store is the metrics registry (mtpu_solver_* series), so the
table is also scraped at /metrics and per-run deltas ride the same
marker machinery everything else uses.
"""

from __future__ import annotations

from typing import Dict, Optional

from mythril_tpu.observe.registry import SOLVER_WALL_BUCKETS, registry

#: the stable origin labels (see module docstring)
ORIGIN_MEMO = "memo"
ORIGIN_HOST_CDCL = "host-cdcl"
ORIGIN_DEVICE = "device-portfolio"
ORIGIN_Z3 = "host-z3"

_QUERIES = None
_WALL = None
_WALL_HIST = None
_ESCALATIONS = None
_METRICS_REG = None


def _metrics():
    # handles re-resolve when the registry instance changes
    # (reset_registry in tests) — a cached child writing to an
    # orphaned registry is a silent telemetry sink
    global _QUERIES, _WALL, _WALL_HIST, _ESCALATIONS, _METRICS_REG
    if _QUERIES is None or _METRICS_REG is not registry():
        reg = _METRICS_REG = registry()
        _QUERIES = reg.counter(
            "mtpu_solver_queries_total",
            "SAT/SMT queries by answering origin and verdict",
        )
        _WALL = reg.counter(
            "mtpu_solver_wall_seconds_total",
            "solver wall seconds by answering origin",
        )
        # per-query wall distribution on its own ladder: memo hits
        # are microseconds, CDCL marathons tens of seconds — the
        # default bucket ladder crushes the warm end into one bucket
        _WALL_HIST = reg.histogram(
            "mtpu_solver_query_seconds",
            "per-query solver wall by answering origin",
            buckets=SOLVER_WALL_BUCKETS,
        )
        _ESCALATIONS = reg.counter(
            "mtpu_solver_escalations_total",
            "queries that climbed past the first ladder rung, by origin",
        )
    return _QUERIES, _WALL, _ESCALATIONS


def record_query(
    origin: str, verdict: str, wall_s: float = 0.0, hop: int = 0
) -> None:
    """Tag one solver query: `origin` answered it with `verdict`
    ("sat"/"unsat"/"unknown"/"timeout") after `wall_s` seconds and
    `hop` escalation rungs. Honors the global observe switch."""
    from mythril_tpu import observe

    if not observe.enabled():
        return
    queries, wall, escalations = _metrics()
    queries.labels(origin=origin, verdict=verdict).inc()
    if wall_s:
        wall.labels(origin=origin).inc(wall_s)
        _WALL_HIST.labels(origin=origin).observe(wall_s)
    if hop > 0:
        escalations.labels(origin=origin).inc(hop)


def marker() -> Dict:
    """Registry snapshot for per-run attribution deltas."""
    _metrics()
    return registry().marker()


def attribution(since: Optional[Dict] = None) -> Dict[str, Dict]:
    """The per-origin attribution table:

        {origin: {"queries": n, "verdicts": {verdict: n},
                  "wall_s": seconds, "escalations": n}}

    Over the whole process, or as a delta when `since` (a `marker()`)
    is given — the per-run form bench.py and the report meta embed."""
    _metrics()
    reg = registry()
    snap = reg.since(since) if since is not None else reg.snapshot()
    out: Dict[str, Dict] = {}

    def row(origin: str) -> Dict:
        entry = out.get(origin)
        if entry is None:
            entry = out[origin] = {
                "queries": 0,
                "verdicts": {},
                "wall_s": 0.0,
                "escalations": 0,
            }
        return entry

    for key, value in (snap.get("mtpu_solver_queries_total") or {}).items():
        labels = dict(key)
        entry = row(labels.get("origin", "?"))
        verdict = labels.get("verdict", "?")
        entry["queries"] += int(value)
        entry["verdicts"][verdict] = (
            entry["verdicts"].get(verdict, 0) + int(value)
        )
    for key, value in (
        snap.get("mtpu_solver_wall_seconds_total") or {}
    ).items():
        row(dict(key).get("origin", "?"))["wall_s"] = round(value, 3)
    for key, value in (
        snap.get("mtpu_solver_escalations_total") or {}
    ).items():
        row(dict(key).get("origin", "?"))["escalations"] = int(value)
    return out
