"""Structured spans + the flight recorder + Perfetto export.

`trace(name, **attrs)` opens one span: monotonic start/end timestamps,
the caller's thread, an optional device-group *track*, and a parent
link (a thread-local stack gives nesting for free), recorded into a
bounded ring buffer — the **flight recorder**. The recorder is always
cheap (a deque append under a lock, nothing per step) and bounded, so
it can run in production and be dumped on demand:

- the service serves the recent tail at ``/trace``;
- ``myth analyze --trace-out trace.json`` exports the whole run;
- a ``MESH_GROUP_DEGRADED`` or deadline degradation triggers an
  automatic dump (``observe.configure(out_dir=...)``), so the
  flight recorder answers "what was in flight when it died".

The export format is Chrome/Perfetto trace-event JSON (`"X"` complete
events with microsecond timestamps): load it at https://ui.perfetto.dev
and a pipelined multi-device run renders as an actual timeline — one
track per device group / thread, wave execution against host harvest,
bubbles and compile stalls visible as gaps.

Span taxonomy (docs/observability.md has the diagram):

    job > contract > explore.run > phase > {wave.dispatch, wave.device,
    wave.harvest, wave.consume, flip.solve.host, flip.solve.device,
    kernel.compile, mesh.chunk, mesh.steal, service.wave}
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

_IDS = itertools.count(1)
_TLS = threading.local()


class Span:
    """One closed span. Timestamps are `time.perf_counter()` seconds
    (monotonic, process-local)."""

    __slots__ = ("sid", "parent", "name", "t0", "t1", "tid", "track", "attrs")

    def __init__(self, sid, parent, name, t0, t1, tid, track, attrs) -> None:
        self.sid = sid
        self.parent = parent
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.tid = tid
        self.track = track
        self.attrs = attrs

    def as_dict(self) -> Dict:
        out = {
            "sid": self.sid,
            "parent": self.parent,
            "name": self.name,
            "t0": round(self.t0, 6),
            "t1": round(self.t1, 6),
            "dur_s": round(self.t1 - self.t0, 6),
            "thread": self.tid,
        }
        if self.track is not None:
            out["track"] = self.track
        if self.attrs:
            out["attrs"] = self.attrs
        return out


class FlightRecorder:
    """Bounded ring of closed spans (newest win; the recorder is a
    flight recorder, not an archive)."""

    def __init__(self, capacity: int = 8192) -> None:
        self._mu = threading.Lock()
        self._ring: "deque[Span]" = deque(maxlen=max(16, capacity))
        self.dropped = 0
        self.recorded = 0

    def record(self, span: Span) -> None:
        with self._mu:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(span)
            self.recorded += 1

    def add(
        self,
        name: str,
        t0: float,
        t1: float,
        track: Optional[str] = None,
        **attrs,
    ) -> None:
        """Record a RETROSPECTIVE span from explicit timestamps — the
        idiom for device execution, whose start (dispatch) and end
        (readback-ready) are observed on the host at different call
        sites."""
        from mythril_tpu import observe

        if not observe.enabled():
            return
        self.record(
            Span(
                next(_IDS), None, name, t0, t1,
                threading.current_thread().name, track, attrs or None,
            )
        )

    def tail(self, n: int = 512) -> List[Span]:
        with self._mu:
            spans = list(self._ring)
        return spans[-n:]

    def dump(self) -> List[Dict]:
        return [span.as_dict() for span in self.tail(len(self._ring))]

    def clear(self) -> None:
        with self._mu:
            self._ring.clear()

    def __len__(self) -> int:
        with self._mu:
            return len(self._ring)


_RECORDER = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    return _RECORDER


def _stack() -> List[int]:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


class _TraceCtx:
    """The `trace()` context manager: pushes a span id on the thread's
    stack at entry (so children see their parent), records the closed
    span at exit. Exceptions propagate; the span still closes and is
    marked with the exception type."""

    __slots__ = ("name", "track", "attrs", "sid", "t0")

    def __init__(self, name: str, track: Optional[str], attrs: Dict) -> None:
        self.name = name
        self.track = track
        self.attrs = attrs

    def __enter__(self) -> "_TraceCtx":
        self.sid = next(_IDS)
        self.t0 = time.perf_counter()
        _stack().append(self.sid)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        t1 = time.perf_counter()
        stack = _stack()
        if stack and stack[-1] == self.sid:
            stack.pop()
        parent = stack[-1] if stack else None
        attrs = self.attrs
        if exc_type is not None:
            attrs = dict(attrs or {}, error=exc_type.__name__)
        _RECORDER.record(
            Span(
                self.sid, parent, self.name, self.t0, t1,
                threading.current_thread().name, self.track, attrs or None,
            )
        )


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NULL = _NullCtx()


def trace(name: str, track: Optional[str] = None, **attrs):
    """Open a structured span. Near-zero-cost no-op while telemetry is
    disabled (one bool check, a shared null context)."""
    from mythril_tpu import observe

    if not observe.enabled():
        return _NULL
    return _TraceCtx(name, track, attrs or None)


# ---------------------------------------------------------------------------
# Perfetto / Chrome trace-event export
# ---------------------------------------------------------------------------
def to_perfetto(spans: Optional[List[Span]] = None) -> Dict:
    """Render spans as Chrome trace-event JSON (the `traceEvents`
    array form Perfetto loads directly): one complete ("ph": "X")
    event per span with microsecond timestamps, plus thread_name
    metadata so tracks are labeled. Spans with a device-group `track`
    render on that track (device timelines beside host threads)."""
    if spans is None:
        spans = _RECORDER.tail(len(_RECORDER))
    events: List[Dict] = []
    tids: Dict[str, int] = {}

    def tid_of(label: str) -> int:
        tid = tids.get(label)
        if tid is None:
            tid = tids[label] = len(tids) + 1
        return tid

    pid = os.getpid()
    base = min((s.t0 for s in spans), default=0.0)
    for span in spans:
        label = span.track if span.track is not None else span.tid
        events.append(
            {
                "name": span.name,
                "ph": "X",
                "ts": int((span.t0 - base) * 1e6),
                "dur": max(1, int((span.t1 - span.t0) * 1e6)),
                "pid": pid,
                "tid": tid_of(label),
                "args": dict(span.attrs or {}, sid=span.sid,
                             parent=span.parent),
            }
        )
    for label, tid in tids.items():
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": label},
            }
        )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"source": "mythril_tpu.observe"},
    }


def export_trace(path: str, spans: Optional[List[Span]] = None) -> str:
    """Write the Perfetto JSON to `path` (atomic tmp+rename, the
    checkpoint writer's idiom) and return the path."""
    doc = to_perfetto(spans)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fp:
        json.dump(doc, fp)
    os.replace(tmp, path)
    return path


def overlap_fraction(
    spans: Optional[List[Span]] = None, name: str = "wave.device"
) -> float:
    """Fraction of the covered time that >= 2 spans named `name` were
    simultaneously open — the span-derived pipelining/mesh overlap
    figure bench.py reports as `trace_overlap_frac`. 0.0 when fewer
    than two such spans exist."""
    if spans is None:
        spans = _RECORDER.tail(len(_RECORDER))
    marks = []
    for span in spans:
        if span.name == name and span.t1 > span.t0:
            marks.append((span.t0, 1))
            marks.append((span.t1, -1))
    if len(marks) < 4:
        return 0.0
    marks.sort()
    covered = overlapped = 0.0
    depth = 0
    prev = marks[0][0]
    for t, d in marks:
        if depth >= 1:
            covered += t - prev
        if depth >= 2:
            overlapped += t - prev
        depth += d
        prev = t
    return round(overlapped / covered, 4) if covered > 0 else 0.0
