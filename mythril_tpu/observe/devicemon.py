"""Device saturation telemetry: the periodic sampler behind the
``mtpu_device_*`` gauges.

The wave counters say what the engine *did*; nothing said how full
the hardware *is* — which is exactly what a federation front needs to
red-line a replica before it falls over. One `DeviceMonitor.sample()`
publishes:

- **Device memory** — per-device ``memory_stats()`` bytes-in-use /
  limit where the backend supports it (TPU/GPU; the CPU backend
  reports none), plus the process RSS from /proc as the
  backend-independent floor every container can alarm on.
- **Arena occupancy** — lanes/stripes busy and jobs resident from the
  service lane allocator (an embedder registers the source).
- **Kernel cache** — pinned buckets and compiles in flight from the
  specialization cache (a compile storm is a saturation signal).
- **Wave overlap / idle fractions** — promoted from per-run
  `ExploreStats` derived fields to live gauges, recomputed from the
  registry's cumulative ``mtpu_explore_*`` counters.

Sources are registered as callables (the same collector idiom the
registry uses) so the monitor never imports the service layer; the
service, the corpus driver and the bench all call `sample()` — the
serve sampler thread does it on a clock.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, List, Optional

from mythril_tpu.observe.registry import MetricsRegistry, registry

log = logging.getLogger(__name__)


def _host_rss_bytes() -> Optional[int]:
    """Resident set size from /proc (Linux); None elsewhere — the
    sampler publishes what it can observe, never guesses."""
    try:
        with open("/proc/self/statm") as fp:
            fields = fp.read().split()
        return int(fields[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        return None


class DeviceMonitor:
    """The mtpu_device_* gauge publisher. `sample()` is cheap (no
    device work beyond memory_stats) and safe to call from any
    thread; `latest()` hands the last sample back as a plain dict for
    /stats and the bench record."""

    def __init__(self, reg: Optional[MetricsRegistry] = None) -> None:
        self._reg = reg
        self._mu = threading.Lock()
        self._arena_source: Optional[Callable[[], Dict]] = None
        self._latest: Dict = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.samples = 0

    @property
    def reg(self) -> MetricsRegistry:
        return self._reg if self._reg is not None else registry()

    def set_arena_source(self, fn: Optional[Callable[[], Dict]]) -> None:
        """Register the lane-allocator occupancy source (the service
        engine's `alloc.occupancy`); None unregisters."""
        with self._mu:
            self._arena_source = fn

    # -- the sample ----------------------------------------------------
    def _sample_device_memory(self, out: Dict) -> None:
        try:
            import jax

            devices = jax.devices()
        except Exception:
            return
        out["devices"] = len(devices)
        self.reg.gauge(
            "mtpu_device_count", "visible accelerator devices"
        ).set(len(devices))
        mem_used = self.reg.gauge(
            "mtpu_device_mem_bytes_in_use",
            "per-device bytes in use (backends with memory_stats)",
        )
        mem_limit = self.reg.gauge(
            "mtpu_device_mem_bytes_limit",
            "per-device memory limit (backends with memory_stats)",
        )
        per_device = {}
        for device in devices:
            stats = None
            try:
                stats = device.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            used = stats.get("bytes_in_use")
            limit = stats.get("bytes_limit") or stats.get(
                "bytes_reservable_limit"
            )
            label = str(device.id)
            if used is not None:
                mem_used.labels(device=label).set(float(used))
            if limit:
                mem_limit.labels(device=label).set(float(limit))
            if used is not None:
                per_device[label] = {
                    "bytes_in_use": int(used),
                    "bytes_limit": int(limit) if limit else None,
                }
        if per_device:
            out["memory"] = per_device

    def _sample_host(self, out: Dict) -> None:
        rss = _host_rss_bytes()
        if rss is not None:
            out["host_rss_bytes"] = rss
            self.reg.gauge(
                "mtpu_device_host_rss_bytes",
                "analyzer process resident set size",
            ).set(float(rss))

    def _sample_arena(self, out: Dict) -> None:
        with self._mu:
            source = self._arena_source
        if source is None:
            return
        try:
            occ = source()
        except Exception:
            log.debug("arena occupancy source failed", exc_info=True)
            return
        lanes = max(1, int(occ.get("lanes", 1)))
        busy = int(occ.get("lanes_busy", 0))
        out["arena"] = {
            "lanes": lanes,
            "lanes_busy": busy,
            "occupancy": round(busy / lanes, 4),
            "jobs_resident": int(occ.get("jobs_resident", 0)),
        }
        self.reg.gauge(
            "mtpu_device_arena_lanes", "arena lane capacity"
        ).set(lanes)
        self.reg.gauge(
            "mtpu_device_arena_lanes_busy", "arena lanes owned by jobs"
        ).set(busy)
        self.reg.gauge(
            "mtpu_device_arena_occupancy",
            "arena lane occupancy fraction (busy/capacity)",
        ).set(busy / lanes)
        self.reg.gauge(
            "mtpu_device_arena_jobs_resident",
            "jobs currently resident in the arena",
        ).set(int(occ.get("jobs_resident", 0)))

    def _sample_kernel_cache(self, out: Dict) -> None:
        try:
            from mythril_tpu.laser.batch.specialize import (
                kernel_cache_stats,
            )

            stats = kernel_cache_stats()
        except Exception:
            return
        out["kernel_cache"] = {
            "size": stats.get("size", 0),
            "pinned": stats.get("pinned", 0),
            "compiles_in_flight": stats.get("compiles_in_flight", 0),
        }
        self.reg.gauge(
            "mtpu_device_kernel_cache_size",
            "specialized-kernel buckets resident in the compile cache",
        ).set(stats.get("size", 0))
        self.reg.gauge(
            "mtpu_device_kernel_cache_pinned",
            "kernel buckets pinned by resident contracts",
        ).set(stats.get("pinned", 0))
        self.reg.gauge(
            "mtpu_device_kernel_compiles_in_flight",
            "specialized-kernel compiles currently running",
        ).set(stats.get("compiles_in_flight", 0))

    def _sample_wave_fractions(self, out: Dict) -> None:
        """wave overlap / device idle, live from the cumulative
        explore counters (the per-run ExploreStats derived ratios,
        promoted to process gauges)."""
        snap = self.reg.snapshot()

        def total(name: str) -> float:
            return float(sum((snap.get(name) or {}).values()))

        busy = total("mtpu_explore_device_busy_s_total")
        overlap = total("mtpu_explore_wave_overlap_s_total")
        wall = total("mtpu_explore_wall_s_total")
        if busy > 0:
            frac = min(1.0, overlap / busy)
            out["wave_overlap_frac"] = round(frac, 4)
            self.reg.gauge(
                "mtpu_device_wave_overlap_frac",
                "fraction of device execution covered by concurrent "
                "host work (cumulative)",
            ).set(frac)
        if wall > 0:
            idle = max(0.0, min(1.0, 1.0 - busy / wall))
            out["idle_frac"] = round(idle, 4)
            self.reg.gauge(
                "mtpu_device_idle_frac",
                "fraction of exploration wall with no wave in flight "
                "(cumulative)",
            ).set(idle)

    def sample(self) -> Dict:
        out: Dict = {}
        for step in (
            self._sample_device_memory,
            self._sample_host,
            self._sample_arena,
            self._sample_kernel_cache,
            self._sample_wave_fractions,
        ):
            try:
                step(out)
            except Exception:  # one broken probe must not sink the rest
                log.debug("device sample step failed", exc_info=True)
        with self._mu:
            self._latest = out
            self.samples += 1
        return out

    def latest(self) -> Dict:
        with self._mu:
            return dict(self._latest)

    # -- the sampler thread --------------------------------------------
    def start(self, interval_s: float = 5.0) -> "DeviceMonitor":
        if self._thread is None:
            self._stop.clear()

            def _loop():
                while not self._stop.wait(interval_s):
                    try:
                        self.sample()
                    except Exception:
                        pass

            self._thread = threading.Thread(
                target=_loop, name="myth-device-sampler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)


_MONITOR = DeviceMonitor()


def device_monitor() -> DeviceMonitor:
    return _MONITOR
