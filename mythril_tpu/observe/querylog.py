"""Solver query flight recorder: capture every SMT query, explain
every lost verdict.

Every bench since r02 reports ``device_sat_verdicts ~ 0`` while the
host CDCL answers thousands of queries. PR 7's per-origin attribution
(`solverstats.py`) can count *who* won; this module records *why the
device lost* — and, under ``--capture-queries DIR``, puts the queries
themselves on disk as content-addressed, replayable artifacts so
portfolio tuning (ROADMAP item 1) iterates on a fixed corpus offline
(`myth solverlab`) instead of re-running full analyses.

Three surfaces:

- **Loss-reason taxonomy** — every host-won (and host-unknown) verdict
  in the `check_terms` funnel is tagged with the reason the device
  portfolio did not answer it, recorded as
  ``mtpu_solver_loss_total{reason, verdict}``. Like the other
  legacy-backing registry arithmetic, the counters stay on under
  ``--no-observe`` so the bench waterfall never changes with telemetry
  off. The catalog:

  =====================  ==================================================
  LOWERING_UNSUPPORTED   the query contains ops outside the device tensor
                         language (or the host blaster fragment)
  BUCKET_OVERFLOW        widths exceed the portfolio's limb cap — no
                         shape bucket can hold the program
  SLS_NONCONVERGED       the portfolio search finished without a witness
                         (a miss proves nothing; the CDCL decided)
  RACE_LOST_TIMING       the portfolio was still searching when the CDCL
                         answered (or the query budget expired)
  SPRINT_PREEMPTED       the conflict-budgeted CDCL sprint answered
                         before any device attempt was affordable
  GATE_DISABLED          device solving switched off (flag, CPU-only
                         backend, or deterministic-solving mode)
  RACE_NOT_STARTED       the race could not start (chip owned by an
                         exploration, in-flight slot taken, no thread)
  WITNESS_INVALID        a device witness failed the reconstruction /
                         soundness gate and the CDCL re-decided
  QUERY_TRIVIAL          answered before any CNF search (constant
                         folding, empty set, sub-race-size query)
  DEADLINE_EXPIRED       the run deadline expired before the solve
  UNCLASSIFIED           safety net — a funnel exit the taxonomy missed
                         (a nonzero count is a bug)
  =====================  ==================================================

- **Query context** — a thread-local tag naming where a query
  originated: ``flip-frontier`` (explorer flip solving), ``module``
  (detection-module queries), ``memo-miss`` (bare get_model solves —
  engine feasibility checks whose memo lookup missed).

- **Capture** — `configure_capture(dir)` arms the recorder: each
  solved query's LOWERED constraint set serializes to
  ``<dir>/q-<sha256>.json`` with its shape-bucket key, origin,
  per-engine verdict/wall/hop observations and loss reason. Artifacts
  are content-addressed on a var-name-canonicalized encoding, so the
  same query captured twice (or from two phases) lands in ONE file
  with appended observations. Capture is off by default and the
  disabled path is a single boolean check — `tools/serve_smoke.py`
  pins that it adds zero registry series.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from contextlib import contextmanager
from typing import Dict, List, Optional

from mythril_tpu.observe.registry import registry

log = logging.getLogger(__name__)

#: artifact schema (bumped when the on-disk shape changes; solverlab
#: refuses to replay a newer major schema)
ARTIFACT_SCHEMA_VERSION = 1

# -- the loss-reason taxonomy (see module docstring) -----------------------
LOSS_LOWERING_UNSUPPORTED = "LOWERING_UNSUPPORTED"
LOSS_BUCKET_OVERFLOW = "BUCKET_OVERFLOW"
LOSS_SLS_NONCONVERGED = "SLS_NONCONVERGED"
LOSS_RACE_LOST_TIMING = "RACE_LOST_TIMING"
LOSS_SPRINT_PREEMPTED = "SPRINT_PREEMPTED"
LOSS_GATE_DISABLED = "GATE_DISABLED"
LOSS_RACE_NOT_STARTED = "RACE_NOT_STARTED"
LOSS_WITNESS_INVALID = "WITNESS_INVALID"
LOSS_QUERY_TRIVIAL = "QUERY_TRIVIAL"
LOSS_DEADLINE_EXPIRED = "DEADLINE_EXPIRED"
LOSS_UNCLASSIFIED = "UNCLASSIFIED"

LOSS_REASONS = (
    LOSS_LOWERING_UNSUPPORTED,
    LOSS_BUCKET_OVERFLOW,
    LOSS_SLS_NONCONVERGED,
    LOSS_RACE_LOST_TIMING,
    LOSS_SPRINT_PREEMPTED,
    LOSS_GATE_DISABLED,
    LOSS_RACE_NOT_STARTED,
    LOSS_WITNESS_INVALID,
    LOSS_QUERY_TRIVIAL,
    LOSS_DEADLINE_EXPIRED,
    LOSS_UNCLASSIFIED,
)

#: the query-origin labels (where a query came FROM, as opposed to the
#: solverstats origin of who ANSWERED it)
QUERY_ORIGIN_FLIP = "flip-frontier"
QUERY_ORIGIN_MODULE = "module"
QUERY_ORIGIN_MEMO_MISS = "memo-miss"


_LOSS = None
_CAPTURED = None
_METRICS_REG = None


def _metrics():
    # handles re-resolve when the registry instance changes
    # (reset_registry in tests) — a cached child writing to an
    # orphaned registry is a silent telemetry sink
    global _LOSS, _CAPTURED, _METRICS_REG
    if _LOSS is None or _METRICS_REG is not registry():
        reg = _METRICS_REG = registry()
        _LOSS = reg.counter(
            "mtpu_solver_loss_total",
            "host-answered solver verdicts by device-loss reason",
        )
        _CAPTURED = reg.counter(
            "mtpu_solver_captured_queries_total",
            "solver queries captured to the flight-recorder corpus",
        )
    return _LOSS, _CAPTURED


def record_loss(reason: str, verdict: str, site: str = "") -> None:
    """Count one host-answered verdict against the loss taxonomy.
    Registry arithmetic that backs the bench waterfall and `/stats
    solver.loss.*` — deliberately NOT gated on the observe switch, so
    ``sum(solver_loss_reasons) == cdcl_sat_verdicts`` holds on every
    bench record."""
    loss, _captured = _metrics()
    loss.labels(reason=reason or LOSS_UNCLASSIFIED, verdict=verdict).inc()


def loss_reasons(
    since: Optional[Dict] = None, verdict: Optional[str] = None
) -> Dict[str, int]:
    """The waterfall: {reason: count}, whole-process or as a delta
    since a registry `marker()`; `verdict="sat"` restricts to
    host-WON queries (the acceptance-criteria view)."""
    _metrics()
    reg = registry()
    snap = reg.since(since) if since is not None else reg.snapshot()
    out: Dict[str, int] = {}
    for key, value in (snap.get("mtpu_solver_loss_total") or {}).items():
        labels = dict(key)
        if verdict is not None and labels.get("verdict") != verdict:
            continue
        reason = labels.get("reason", LOSS_UNCLASSIFIED)
        out[reason] = out.get(reason, 0) + int(value)
    return out


def captured_total(since: Optional[Dict] = None) -> int:
    """Queries captured to disk (process total or delta)."""
    _metrics()
    reg = registry()
    snap = reg.since(since) if since is not None else reg.snapshot()
    return int(
        sum((snap.get("mtpu_solver_captured_queries_total") or {}).values())
    )


# ---------------------------------------------------------------------------
# query context: where did this query come from
# ---------------------------------------------------------------------------

_CTX = threading.local()


def current_origin() -> str:
    """The innermost query-context tag; bare solves (engine
    feasibility checks) default to memo-miss — they reached the solver
    because the get_model memo missed."""
    stack = getattr(_CTX, "stack", None)
    return stack[-1] if stack else QUERY_ORIGIN_MEMO_MISS


@contextmanager
def query_context(origin: str, only_if_root: bool = False):
    """Tag queries issued inside the block with `origin`. With
    `only_if_root` the tag applies only when no enclosing context set
    one (get_model's memo-miss default must not mask the module/flip
    tags of its callers)."""
    stack = getattr(_CTX, "stack", None)
    if stack is None:
        stack = _CTX.stack = []
    if only_if_root and stack:
        yield
        return
    stack.append(origin)
    try:
        yield
    finally:
        stack.pop()


# ---------------------------------------------------------------------------
# capture configuration
# ---------------------------------------------------------------------------

_CAPTURE_DIR: Optional[str] = None
_CAPTURE_MU = threading.Lock()
#: per-artifact observation cap: a hot memo-missing query re-posed
#: hundreds of times must not grow its artifact unboundedly
MAX_OBSERVATIONS = 16


def configure_capture(out_dir: Optional[str]) -> None:
    """Arm (or, with None, disarm) query capture into `out_dir`."""
    global _CAPTURE_DIR
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    _CAPTURE_DIR = out_dir or None


def capture_dir() -> Optional[str]:
    return _CAPTURE_DIR


def capture_enabled() -> bool:
    return _CAPTURE_DIR is not None


# ---------------------------------------------------------------------------
# term (de)serialization: the replayable program
# ---------------------------------------------------------------------------


def serialize_terms(lowered) -> Dict:
    """Flatten a lowered constraint set into a JSON-able DAG: one node
    per interned term in topological order, term args as ["t", idx],
    ints as ["i", n], names as ["s", name]. Raises NotImplementedError
    on payloads outside (Term | int | str) — post-`lower` sets never
    hold any."""
    from mythril_tpu.laser.smt.terms import Term

    order: List = []
    index: Dict[int, int] = {}
    for root in lowered:
        stack = [(root, False)]
        while stack:
            node, expanded = stack.pop()
            if node._id in index:
                continue
            if expanded:
                if node._id not in index:
                    index[node._id] = len(order)
                    order.append(node)
                continue
            stack.append((node, True))
            for a in node.args:
                if isinstance(a, Term) and a._id not in index:
                    stack.append((a, False))

    nodes = []
    for t in order:
        args = []
        for a in t.args:
            if isinstance(a, Term):
                args.append(["t", index[a._id]])
            elif isinstance(a, bool):
                args.append(["i", int(a)])
            elif isinstance(a, int):
                args.append(["i", a])
            elif isinstance(a, str):
                args.append(["s", a])
            else:
                raise NotImplementedError(
                    f"unserializable payload {type(a).__name__} in {t.op}"
                )
        nodes.append({"op": t.op, "w": t.width or 0, "a": args})
    return {
        "nodes": nodes,
        "roots": [index[c._id] for c in lowered],
    }


def content_address(doc: Dict) -> str:
    """sha256 of the program with var NAMES canonicalized to their
    first-occurrence index: the preprocessor's gensym'd fresh names
    (select/UF elimination) differ run to run, but the query they
    encode is the same query — and must dedup to the same artifact."""
    rename: Dict[str, str] = {}
    canon_nodes = []
    for node in doc["nodes"]:
        args = []
        for kind, value in node["a"]:
            if kind == "s":
                if value not in rename:
                    rename[value] = f"v{len(rename)}"
                value = rename[value]
            args.append([kind, value])
        canon_nodes.append([node["op"], node["w"], args])
    blob = json.dumps(
        [canon_nodes, doc["roots"]], separators=(",", ":"), sort_keys=True
    )
    return hashlib.sha256(blob.encode()).hexdigest()


def deserialize_terms(doc: Dict) -> List:
    """Rebuild real (interned) terms from a serialized program; the
    constructors re-apply their canonicalizations, so the rebuilt set
    is semantically the captured set even across processes."""
    from mythril_tpu.laser.smt import terms

    built: List = []

    def arg(spec):
        kind, value = spec
        return built[value] if kind == "t" else value

    for node in doc["nodes"]:
        op, w, raw = node["op"], node["w"], node["a"]
        a = [arg(s) for s in raw]
        if op == "const":
            t = terms.bv_const(a[0], w)
        elif op == "var":
            t = terms.bv_var(a[0], w)
        elif op == "bvar":
            t = terms.bool_var(a[0])
        elif op == "true":
            t = terms.TRUE
        elif op == "false":
            t = terms.FALSE
        elif op == "extract":
            t = terms.extract(a[0], a[1], a[2])
        elif op in ("zext", "sext"):
            t = getattr(terms, op)(a[0], a[1])
        elif op == "ite":
            t = terms.ite(a[0], a[1], a[2])
        elif op in ("band", "bor"):
            t = getattr(terms, op)(*a)
        elif op == "bnot":
            t = terms.bnot(a[0])
        elif op == "not":
            t = terms.bvnot(a[0])
        elif op in _BIN_OPS:
            t = _BIN_OPS[op](a[0], a[1])
        else:
            raise NotImplementedError(f"cannot rebuild op {op!r}")
        built.append(t)
    return [built[i] for i in doc["roots"]]


def _bin_ops():
    from mythril_tpu.laser.smt import terms

    return {
        "add": terms.add, "sub": terms.sub, "mul": terms.mul,
        "udiv": terms.udiv, "urem": terms.urem, "sdiv": terms.sdiv,
        "srem": terms.srem, "and": terms.bvand, "or": terms.bvor,
        "xor": terms.bvxor, "shl": terms.shl, "lshr": terms.lshr,
        "ashr": terms.ashr, "concat": terms.concat, "eq": terms.eq,
        "ult": terms.ult, "ule": terms.ule, "slt": terms.slt,
        "sle": terms.sle, "bxor": terms.bxor,
    }


class _LazyBin(dict):
    def __missing__(self, key):
        self.update(_bin_ops())
        return dict.__getitem__(self, key)

    def __contains__(self, key):
        if not len(self):
            self.update(_bin_ops())
        return dict.__contains__(self, key)


_BIN_OPS = _LazyBin()


# ---------------------------------------------------------------------------
# capture
# ---------------------------------------------------------------------------


def _bucket_info(lowered):
    """(shape-bucket key, compile-loss reason) of the query as the
    portfolio sees it — the bucket the replay lab groups engines by."""
    from mythril_tpu.laser.smt.solver import portfolio

    prog, reason = portfolio.compile_program_ex(lowered)
    if prog is None:
        return None, reason
    return portfolio.bucket_key(prog), None


def capture_query(
    lowered,
    engine: str,
    verdict: str,
    wall_s: float = 0.0,
    hop: int = 0,
    loss_reason: Optional[str] = None,
    site: str = "",
    origin: Optional[str] = None,
    detail: Optional[Dict] = None,
) -> Optional[str]:
    """Serialize one solved query into the capture corpus (no-op when
    capture is off). `detail` is a small JSON-able dict attached to
    the observation (e.g. the actual sprint cap behind a
    SPRINT_PREEMPTED loss). Returns the artifact path, or None. Never
    raises: capture must never sink a query."""
    out_dir = _CAPTURE_DIR
    if out_dir is None or not lowered:
        # a fully-propagated (empty) query is a trivial sat — there is
        # nothing to replay
        return None
    try:
        doc = serialize_terms(lowered)
        sha = content_address(doc)
        observation = {
            "engine": engine,
            "verdict": verdict,
            "wall_s": round(float(wall_s), 6),
            "hop": int(hop),
            "loss_reason": loss_reason,
            "site": site,
        }
        if detail:
            observation["detail"] = dict(detail)
        path = os.path.join(out_dir, f"q-{sha}.json")
        with _CAPTURE_MU:
            if os.path.exists(path):
                with open(path) as fp:
                    artifact = json.load(fp)
            else:
                bucket, compile_loss = _bucket_info(lowered)
                artifact = {
                    "schema_version": ARTIFACT_SCHEMA_VERSION,
                    "kind": "mtpu-solver-query",
                    "sha": sha,
                    "origin": origin or current_origin(),
                    "n_constraints": len(doc["roots"]),
                    "n_nodes": len(doc["nodes"]),
                    "bucket": bucket,
                    "compile_loss": compile_loss,
                    "program": doc,
                    "observations": [],
                }
            obs = artifact["observations"]
            if len(obs) < MAX_OBSERVATIONS:
                obs.append(observation)
            else:
                obs[-1] = observation
            artifact["verdict"] = verdict
            artifact["loss_reason"] = loss_reason
            tmp = path + ".tmp"
            with open(tmp, "w") as fp:
                json.dump(artifact, fp, sort_keys=True)
            os.replace(tmp, path)
        _loss, captured = _metrics()
        captured.labels(origin=artifact["origin"]).inc()
        return path
    except Exception:
        log.debug("query capture failed", exc_info=True)
        return None


def load_corpus(
    corpus_dir: str,
    reason: Optional[str] = None,
    origin: Optional[str] = None,
) -> List[Dict]:
    """Load a captured corpus (sorted by content address), optionally
    filtered by last loss reason and/or query origin."""
    out: List[Dict] = []
    for name in sorted(os.listdir(corpus_dir)):
        if not (name.startswith("q-") and name.endswith(".json")):
            continue
        path = os.path.join(corpus_dir, name)
        try:
            with open(path) as fp:
                artifact = json.load(fp)
        except Exception:
            log.warning("unreadable capture artifact skipped: %s", path)
            continue
        if artifact.get("kind") != "mtpu-solver-query":
            continue
        if int(artifact.get("schema_version", 0)) > ARTIFACT_SCHEMA_VERSION:
            log.warning("artifact %s has a newer schema; skipped", name)
            continue
        if reason is not None and artifact.get("loss_reason") != reason:
            continue
        if origin is not None and artifact.get("origin") != origin:
            continue
        out.append(artifact)
    return out
