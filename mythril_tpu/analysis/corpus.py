"""Corpus-parallel analysis: many contracts at once.

The reference analyzes contracts strictly sequentially
(mythril/mythril/mythril_analyzer.py:145-185 — a plain for-loop);
SURVEY.md §2.4 maps that loop onto two axes here:

1. **Device axis** — the parent process (which owns the accelerator)
   runs ONE lane-striped symbolic exploration over the whole corpus
   (laser/batch/explore.py DeviceCorpusExplorer): every contract gets
   a stripe of lanes, each wave advances the entire corpus in one
   jit'd dispatch, and the banked witnesses + branch coverage are
   handed to the host analyses.
2. **Host axis** — the per-contract SymExecWrapper + fire_lasers
   pipeline. Single-process runs get each contract's prepass outcome
   injected (witness issues + coverage-guided pruning); pooled runs
   overlap the prepass with the workers and merge its witnesses into
   the results afterward (workers never touch the device; the chip is
   a parent-process resource).
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
import time
import traceback
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)


def _effective_cpus() -> int:
    """CPUs this process may actually run on — the affinity mask, not
    the host count (a container pinned to one core of a 64-core host
    must take the single-core paths)."""
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return mp.cpu_count()


#: corpus size from which the overlapped prepass pays even on a
#: single-core box: the waves are device-bound (GIL released during
#: dispatch + readback — measured ~2.7s of host-side work per ~33s
#: wave at 3328 lanes), so the per-wave contention tax amortizes over
#: many host analyses, where on a small corpus it dominates
OVERLAP_MIN_CORPUS = 32


def resolve_prepass_budget_s(
    n_contracts: int,
    override: Optional[float] = None,
    execution_timeout: Optional[float] = None,
    ownership: bool = False,
) -> float:
    """Default ACTIVE-time budget (waves + flip solving; lock waits
    don't bill) for the striped corpus prepass.

    With `ownership` (the round-5 inversion), the economics change:
    every contract the exploration completes refunds its WHOLE host
    walk (up to execution_timeout each), so the budget scales with the
    walk ceiling — up to half the refundable wall, bounded per corpus
    size. Early exits (per-contract parking, frontier exhaustion,
    coverage plateau) stop the spend well short of the budget on
    corpora that converge, so the bound mostly prices the hopeless
    tail.

    Witness-injection-only mode (ownership off) keeps the old curve:
    small corpora 1s/contract (the selector seeds cover most of what
    wave 1 reaches; every active second contends with overlapped host
    analyses on a small box), large corpora 0.5s/contract capped at
    120s."""
    if override is not None:
        return override
    n = max(1, n_contracts)
    if ownership and execution_timeout:
        return min(0.5 * execution_timeout * n, 30.0 + 5.0 * n, 300.0)
    if n >= OVERLAP_MIN_CORPUS:
        # floored at the small-corpus cap so crossing the threshold
        # never SHRINKS the budget (32 contracts must not explore less
        # than 31)
        return min(120.0, max(30.0, 0.5 * n))
    return min(30.0, 1.0 * n)


def _runnable_rows(
    contracts: List[Tuple[str, str, str]],
) -> List[Tuple[int, str]]:
    """(index, normalized runtime hex) for every contract the device
    prepass can execute — THE filter both the prepass and its budget/
    window sizing must share, or the two silently desync."""
    rows = []
    for idx, (code, _creation, _name) in enumerate(contracts):
        code = code[2:] if code.startswith("0x") else code
        if len(code) >= 8:
            rows.append((idx, code))
    return rows


def corpus_shard(items, shard_index: int, shard_count: int, identity=None):
    """Deterministic multi-host partition of a corpus — the DCN axis of
    SURVEY §2.4's per-contract-loop mapping: contracts are
    embarrassingly parallel across hosts, so scale-out is a stable
    partition + a report merge, with no cross-host traffic during
    analysis (the reference's analog is running its sequential loop on
    a slice of the input list).

    Assignment hashes each item's CONTENT (name + runtime code), not
    its position, so every host computes the same partition no matter
    how its filesystem enumerates the inputs. `identity` maps an item
    to its identity string; the default fits the analyze_corpus row
    shape (code, creation, name).
    """
    import hashlib

    if shard_count < 1:
        raise ValueError(f"shard count must be >= 1, got {shard_count}")
    if not 0 <= shard_index < shard_count:
        raise ValueError(
            f"shard index {shard_index} outside 0..{shard_count - 1}"
        )
    if shard_count == 1:
        return list(items)
    if identity is None:
        identity = lambda row: f"{row[2]}:{row[0]}"  # noqa: E731
    out = []
    for item in items:
        digest = hashlib.sha256(identity(item).encode()).digest()
        if int.from_bytes(digest[:8], "big") % shard_count == shard_index:
            out.append(item)
    return out


def corpus_device_prepass(
    contracts: List[Tuple[str, str, str]],
    budget_s: Optional[float] = None,
    lanes_per_contract: Optional[int] = None,
    address: int = 0x901D573B8CE8C997DE5F19173C32D966B4FA55FE,
    transaction_count: int = 1,
    host_lock=None,
    stop_event=None,
    publish=None,
    lock_wanted=None,
    execution_timeout: Optional[float] = None,
    ownership: bool = False,
    deadline=None,
    checkpoint_path=None,
    mesh_groups: Optional[int] = None,
    selector_masks: Optional[Dict[int, Tuple]] = None,
) -> Dict[int, Dict]:
    """One striped device exploration over the corpus; returns
    {contract_index: single-contract prepass outcome} for injection
    into the per-contract analyses (indexed, not named — corpus rows
    may share names). Empty on any failure — the host pipeline must
    never be blocked by the device.

    `mesh_groups > 1` (or `--devices N` via the global flag bag) runs
    the multi-chip corpus scheduler instead of one lane-sharded
    engine: the corpus shards over N device groups at admission, each
    group runs its own wave engine in its own failure domain, and a
    drained group steals pending contracts/frontiers from the most
    loaded one (parallel/scheduler.py).

    `selector_masks` ({contract index: (unchanged selector bytes,
    entry directions)}, mythril_tpu/store) restricts specific
    contracts' exploration to their CHANGED functions — the verdict
    store's incremental tier. The mesh scheduler path drops the masks
    (pure optimization; sharded index bookkeeping isn't worth the
    coupling there yet)."""
    runnable = _runnable_rows(contracts)
    if not runnable:
        return {}
    if mesh_groups is None:
        from mythril_tpu.support.support_args import args as _flags

        mesh_groups = getattr(_flags, "mesh_devices", None)
    if budget_s is None:
        budget_s = resolve_prepass_budget_s(
            len(runnable),
            execution_timeout=execution_timeout,
            ownership=ownership,
        )
    if lanes_per_contract is None:
        # corpus-sized waves: the symbolic kernel is lane-bound on a
        # tunneled link (~33s/wave at 3328 lanes), so wide stripes at
        # hundreds of contracts would starve the wave count; narrower
        # stripes keep several waves per transaction phase
        lanes_per_contract = 16 if len(runnable) >= 64 else 32
    if mesh_groups is not None and mesh_groups > 1 and len(runnable) > 1:
        # the multi-chip corpus scheduler: one wave engine per device
        # group, admission-time sharding, live work stealing, per-group
        # failure domains — the same outcome contract as the single
        # engine below, plus stats["mesh"] observability
        return _mesh_prepass(
            runnable,
            mesh_groups=mesh_groups,
            budget_s=budget_s,
            lanes_per_contract=lanes_per_contract,
            address=address,
            transaction_count=transaction_count,
            host_lock=host_lock,
            stop_event=stop_event,
            publish=publish,
            lock_wanted=lock_wanted,
            deadline=deadline,
            checkpoint_path=checkpoint_path,
        )
    # multi-chip: when the backend exposes more than one device, the
    # striped wave shards lane-major over the dp mesh (SURVEY §2.4's
    # per-contract-loop axis) — the single-chip path is the mesh path
    # with one device, so `myth analyze`/analyze_corpus pick the mesh
    # up with no extra configuration
    n_devices = None
    try:
        import jax

        if len(jax.devices()) > 1:
            # shard_batch requires the mesh size to divide the lane
            # count; shrink to the largest divisor rather than letting
            # a non-dividing device count sink the whole prepass into
            # the broad except below (silent host-only degradation)
            n_lanes = len(runnable) * lanes_per_contract
            n_devices = len(jax.devices())
            while n_devices > 1 and n_lanes % n_devices:
                n_devices -= 1
            if n_devices <= 1:
                n_devices = None
    except Exception:
        pass
    try:
        from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer

        translate = (
            None
            if publish is None
            else (lambda ti, outcome: publish(runnable[ti][0], outcome))
        )
        from mythril_tpu.laser.batch.explore import required_calldata_len

        at_scale = len(runnable) >= OVERLAP_MIN_CORPUS
        # translate contract-index masks to track indices (the
        # explorer only sees the runnable rows)
        track_masks = None
        if selector_masks:
            track_masks = {
                ti: selector_masks[idx]
                for ti, (idx, _code) in enumerate(runnable)
                if idx in selector_masks
            }
        explorer = DeviceCorpusExplorer(
            [code for _, code in runnable],
            calldata_len=max(
                required_calldata_len(code) for _, code in runnable
            ),
            # corpus scale runs LEAN-CAP symbolic waves: the
            # [N, mem_cap] memory array dominates per-step wave cost
            # on the tunneled link (explore.py cap notes), and the
            # degraded-lane counters report what the lean trade
            # excludes. Small corpora keep the roomy caps — depth per
            # contract matters more than wave cost there.
            mem_cap=4096 if at_scale else 16384,
            storage_cap=64 if at_scale else 128,
            lanes_per_contract=lanes_per_contract,
            # the budget (active time) is the real limiter; the wave
            # cap only backstops a runaway phase. 8 waves starved the
            # ownership gate: frontier closure + poison seeding need
            # however many waves the budget affords.
            waves=48,
            steps_per_wave=512,
            budget_s=budget_s,
            address=address,
            transaction_count=transaction_count,
            n_devices=n_devices,
            host_lock=host_lock,
            stop_event=stop_event,
            publish=translate,
            deadline=deadline,
            checkpoint_path=checkpoint_path,
            selector_masks=track_masks,
        )
        if lock_wanted is not None:
            explorer.lock_wanted = lock_wanted
        from mythril_tpu.observe.spans import trace

        with trace("corpus.prepass", contracts=len(runnable)):
            result = explorer.run()
    except Exception:
        from mythril_tpu.support.resilience import (
            DegradationLog,
            DegradationReason,
        )

        DegradationLog().record(
            DegradationReason.PREPASS_FAILED, site="corpus-prepass"
        )
        log.warning("corpus device prepass failed", exc_info=True)
        return {}
    stats = result["stats"]
    # mesh observability parity with the scheduler path: the single
    # lane-sharded engine is one group with zero steals, and its
    # occupancy is the fraction of the run a wave was in flight —
    # bench.py reads these fields regardless of which path ran
    wall = stats.get("wall_s") or 0.0
    busy = stats.get("device_busy_s") or 0.0
    stats.setdefault("mesh_devices", n_devices or 1)
    stats.setdefault("mesh_groups", 1)
    stats.setdefault("steal_count", 0)
    stats.setdefault("rebalance_bytes", 0)
    stats.setdefault(
        "mesh",
        {
            "devices": n_devices or 1,
            "groups": 1,
            "steals": 0,
            "stolen_items": 0,
            "rebalance_bytes": 0,
            "per_device": [
                {
                    "group": 0,
                    "devices": n_devices or 1,
                    "waves": stats.get("waves", 0),
                    "device_steps": stats.get("device_steps", 0),
                    "busy_s": round(busy, 3),
                    "occupancy": (
                        round(min(1.0, busy / wall), 3) if wall > 0 else 0.0
                    ),
                    "steals": 0,
                    "faults": stats.get("device_faults", 0),
                }
            ],
        },
    )
    log.info(
        "Corpus device prepass: %d contracts, %d lane-steps over %d waves "
        "in %.1fs, %d branch directions covered",
        len(runnable),
        stats["device_steps"],
        stats["waves"],
        stats["wall_s"],
        stats["branches_covered"],
    )
    outcomes = {}
    for (idx, _code), outcome in zip(runnable, result["contracts"]):
        # the stats block is CORPUS-WIDE (one striped exploration);
        # it rides along on every outcome for observability, marked so
        # consumers don't sum it per contract
        outcome["stats"] = dict(stats, scope="corpus")
        outcomes[idx] = outcome
    return outcomes


def _mesh_prepass(
    runnable,
    mesh_groups: int,
    budget_s: Optional[float],
    lanes_per_contract: int,
    address: int,
    transaction_count: int,
    host_lock,
    stop_event,
    publish,
    lock_wanted,
    deadline,
    checkpoint_path,
) -> Dict[int, Dict]:
    """The multi-chip corpus prepass: shard the runnable rows over
    `mesh_groups` device groups and run one wave engine per group with
    live work stealing (parallel/scheduler.py). Outcome contract
    matches corpus_device_prepass's single-engine path."""
    try:
        from mythril_tpu.parallel.scheduler import CorpusScheduler

        at_scale = len(runnable) >= OVERLAP_MIN_CORPUS
        translate = (
            None
            if publish is None
            else (lambda ti, outcome: publish(runnable[ti][0], outcome))
        )
        scheduler = CorpusScheduler(
            [code for _, code in runnable],
            n_groups=mesh_groups,
            budget_s=budget_s,
            host_lock=host_lock,
            stop_event=stop_event,
            publish=translate,
            lock_wanted=lock_wanted,
            deadline=deadline,
            checkpoint_path=checkpoint_path,
            explorer_kwargs=dict(
                lanes_per_contract=lanes_per_contract,
                mem_cap=4096 if at_scale else 16384,
                storage_cap=64 if at_scale else 128,
                waves=48,
                steps_per_wave=512,
                address=address,
                transaction_count=transaction_count,
            ),
        )
        from mythril_tpu.observe.spans import trace

        with trace(
            "corpus.prepass", contracts=len(runnable), mesh=mesh_groups
        ):
            result = scheduler.run()
    except Exception:
        from mythril_tpu.support.resilience import (
            DegradationLog,
            DegradationReason,
        )

        DegradationLog().record(
            DegradationReason.PREPASS_FAILED, site="corpus-mesh-prepass"
        )
        log.warning("multi-chip corpus prepass failed", exc_info=True)
        return {}
    stats = result["stats"]
    mesh = stats.get("mesh", {})
    log.info(
        "Mesh corpus prepass: %d contracts over %d device group(s), "
        "%d lane-steps / %d waves in %.1fs, %d steal event(s), "
        "%d rebalance byte(s)",
        len(runnable),
        mesh.get("groups", 1),
        stats.get("device_steps", 0),
        stats.get("waves", 0),
        stats.get("wall_s", 0.0),
        mesh.get("steals", 0),
        mesh.get("rebalance_bytes", 0),
    )
    outcomes = {}
    for (idx, _code), outcome in zip(runnable, result["contracts"]):
        outcome["stats"] = dict(stats, scope="corpus")
        outcomes[idx] = outcome
    return outcomes


class OverlappedPrepass:
    """Own the striped device prepass thread beside a sequence of host
    analyses in THIS process.

    The prepass explores the whole corpus on device while the caller
    analyzes contracts one by one; both sides serialize host symbolic
    state on HOST_SYMBOLIC_LOCK (support/host_lock.py). Per-contract
    outcomes are published incrementally after every wave, so analyses
    that start mid-prepass still get witness/coverage injection, and
    `finish()` returns the final outcomes for a post-merge.

    Usage:
        pre = OverlappedPrepass(contracts, address, transaction_count)
        for i, c in enumerate(contracts):
            outcome, device_ok = pre.outcome_for(i)
            with pre.lock:
                ...analyze c with prepass_outcome=outcome, device off
                   unless device_ok...
            pre.yield_lock()
        final = pre.finish()
    """

    def __init__(
        self,
        contracts: List[Tuple[str, str, str]],
        address: int,
        transaction_count: int,
        budget_s: Optional[float] = None,
        execution_timeout: Optional[float] = None,
        ownership: bool = False,
        deadline=None,
        mesh_groups: Optional[int] = None,
        selector_masks: Optional[Dict[int, Tuple]] = None,
    ) -> None:
        import threading

        from mythril_tpu.support.host_lock import HOST_SYMBOLIC_LOCK

        self.lock = HOST_SYMBOLIC_LOCK
        self._final: Dict[int, Dict] = {}
        self._published: Dict[int, Dict] = {}
        self._stop = threading.Event()
        self._lock_wanted = threading.Event()
        self._deviceless = 0
        self._finished = False
        self._drain_abandoned = False

        def _work():
            self._final.update(
                corpus_device_prepass(
                    contracts,
                    budget_s=budget_s,
                    address=address,
                    transaction_count=transaction_count,
                    host_lock=self.lock,
                    stop_event=self._stop,
                    publish=self._published.__setitem__,
                    lock_wanted=self._lock_wanted,
                    execution_timeout=execution_timeout,
                    ownership=ownership,
                    deadline=deadline,
                    mesh_groups=mesh_groups,
                    selector_masks=selector_masks,
                )
            )

        self._thread = threading.Thread(target=_work, daemon=True)
        self._thread.start()

    @property
    def drain_abandoned(self) -> bool:
        """True once a drain timed out on a hung device call — no
        further outcomes will ever be published."""
        return self._drain_abandoned

    def _done(self) -> bool:
        if self._thread is not None and not self._thread.is_alive():
            self._thread.join()
            self._thread = None
        return self._thread is None

    def drain(self) -> None:
        """Block until the prepass finishes its remaining active
        budget, without stopping it early. While the caller waits here
        the lock stays free, so the drain runs at full speed — this is
        how the analysis loop bounds its overlap window: cheap
        contracts share the core with the prepass, then one drain, and
        the budget-bound heavyweights run uncontended with the FINAL
        outcome. (An active-time budget alone cannot bound the
        prepass's wall span: lock waits don't bill, so a 13s budget
        can stretch across a whole corpus of analyses.) The join is
        bounded AND paid once: a device call hung on a crashed tunnel
        must cost the corpus two minutes total, not two minutes per
        remaining contract — after a timed-out drain every later call
        is a no-op and the analyses continue on partial outcomes."""
        if self._drain_abandoned or self._thread is None:
            return
        self._thread.join(timeout=120)
        if not self._done():
            self._drain_abandoned = True
            log.warning(
                "corpus device prepass drain timed out; continuing on "
                "partial outcomes (later drains skipped)"
            )

    def outcome_for(self, i: int):
        """(outcome to inject for contract i, device allowed).

        While the prepass runs, analyses get the latest PUBLISHED
        partial outcome with the device off — the chip belongs to the
        prepass thread, and an injected outcome bypasses the
        device_prepass mode check anyway. Once it's done, the device
        comes back for everyone: covered contracts get the final
        outcome (which skips their own per-contract prepass), missed
        ones fall back to the normal per-contract device path."""
        if self._done():
            return self._final.get(i), True
        self._deviceless += 1
        return self._published.get(i), False

    def yield_lock(self) -> None:
        """Hand the lock to the prepass thread between analyses:
        CPython locks are unfair and a tight loop would reacquire
        within microseconds, rationing the prepass to one reseed per
        contract (lock convoy). Only yields when a flip burst is
        actually waiting — an unconditional sleep would tax every
        analysis of a large corpus for a lock the prepass wants at
        most once per wave."""
        if (
            self._thread is not None
            and self._thread.is_alive()
            and self._lock_wanted.is_set()
        ):
            time.sleep(0.05)

    def finish(self) -> Dict[int, Dict]:
        """Stop the exploration at its next wave boundary and return
        the final per-contract outcomes (empty on prepass failure).
        Idempotent — callers invoke it from finally blocks so an
        exception escaping the analysis loop cannot orphan the
        thread."""
        if self._finished:
            return self._final
        self._finished = True
        if self._thread is not None:
            self._stop.set()
            # stop is honored between waves; one corpus wave runs
            # ~30-60s, so 90s means "a wave and slack", while a hung
            # tunnel call is abandoned instead of stalling the corpus.
            # A thread a drain already waited 120s on is known hung —
            # its device call cannot observe the stop event, so another
            # 90s here would break drain()'s "two minutes total" bound.
            self._thread.join(timeout=0.1 if self._drain_abandoned else 90)
            if self._thread.is_alive():
                log.warning(
                    "corpus device prepass did not stop within its "
                    "grace period; its banked witnesses are lost and "
                    "the daemon thread may briefly keep the device busy"
                )
            self._thread = None
        if not self._final and self._deviceless:
            # the prepass died without outcomes: these analyses ran
            # host-only on at most a partial outcome — say so rather
            # than degrade silently
            log.warning(
                "corpus device prepass produced no outcomes; %d "
                "contract(s) were analyzed without the device",
                self._deviceless,
            )
        return self._final


def _ownership_enabled(use_device: bool) -> bool:
    """Resolve --device-ownership (auto = follow the device axis)."""
    from mythril_tpu.support.support_args import args

    mode = getattr(args, "device_ownership", "auto")
    if mode == "never":
        return False
    if mode == "always":
        return True
    return bool(use_device)


def _outcome_owns(outcome: Optional[Dict]) -> bool:
    """True when a FINAL prepass outcome covered the contract
    end-to-end (explore.py `device_complete`): frontier closed, no
    degraded lanes, no dropped carries. Partial (mid-exploration)
    outcomes never own — UNLESS the explorer froze this contract early
    (`final_for_contract`: all gates green in the last phase, track
    parked, evidence immutable), which is per-contract finality inside
    a still-running corpus exploration."""
    return bool(
        outcome
        and outcome.get("device_complete")
        and (
            outcome.get("final_for_contract")
            or not (outcome.get("stats") or {}).get("partial")
        )
    )


def _maybe_ownable(outcome: Optional[Dict]) -> bool:
    """Could a still-running prepass still hand this contract over?
    False the moment a published outcome shows a hard ownership
    failure (degraded lanes, dropped carries, saturated event bank) —
    those gates only ever get worse, so the host walk should start
    immediately instead of waiting out the prepass."""
    if outcome is None:
        return True  # no information yet
    gates = outcome.get("completeness_gates") or {}
    return (
        gates.get("no_degraded", True)
        and gates.get("no_carry_overflow", True)
        and gates.get("no_event_overflow", True)
    )


def _owned_result(code, creation_code, name, outcome, address) -> Dict:
    """The analysis result for a device-owned contract: issues are
    synthesized from the banked concrete evidence (witness issues +
    evidence issues, analysis/prepass.py / analysis/evidence.py); the
    host walk is SKIPPED — this is the round-5 inversion of the
    reference's per-contract loop (mythril_analyzer.py:145-185)."""
    from mythril_tpu.analysis.prepass import witness_issues
    from mythril_tpu.ethereum.evmcontract import EVMContract

    try:
        contract = EVMContract(
            code=code or "", creation_code=creation_code or "", name=name
        )
        issues = witness_issues(contract, outcome, address)
    except Exception:
        # synthesis failed AFTER the walk was skipped on its promise:
        # None tells the caller to fall back to the host walk
        log.warning("owned-result synthesis failed for %s", name, exc_info=True)
        return None
    stats = dict(outcome.get("stats") or {}, scope="corpus", owned=True)
    return {
        "name": name,
        "issues": [issue.as_dict for issue in issues],
        "states": 0,
        "device_prepass": stats,
        "phases": {},
        "precovered_skips": 0,
        "owned": True,
        "error": None,
    }


def _static_answer_result(name: str, summary, wall_s: float) -> Dict:
    """The result slot for a statically-answered contract: the
    semantic screen (analysis/static taint + sink predicates) proved
    that NO detection module can fire, so the empty issue set IS the
    analysis — no device wave, no host walk, no solver. Same shape as
    an analyzed result so report builders need no special case; the
    `static_answered` flag routes it in the routing feature log and
    the report meta."""
    return {
        "name": name,
        "issues": [],
        "states": 0,
        "device_prepass": None,
        "phases": {},
        "precovered_skips": 0,
        "wall_s": round(wall_s, 6),
        "error": None,
        "static_answered": True,
        "static_analysis": {
            "code_hash": summary.code_hash,
            "static_answerable": True,
            "modules_applicable": 0,
            "wall_ms": summary.wall_ms,
        },
    }


def _static_triage(
    contracts: List[Tuple[str, str, str]],
    skip: Optional[frozenset] = None,
) -> Dict[int, Dict]:
    """{index: static-answer result} for every corpus row the
    semantic screen settles outright. Runs BEFORE the device prepass
    so answered contracts never occupy a lane; any per-contract
    failure simply keeps that contract on the full path. `skip` rows
    (already settled by an earlier tier — the verdict store) are
    never re-examined."""
    from mythril_tpu.analysis.static import summary_for
    from mythril_tpu.observe.registry import registry

    out: Dict[int, Dict] = {}
    counter = registry().counter(
        "mtpu_static_answered_total",
        "contracts settled by the static-answer triage tier",
    )
    for i, (code, creation_code, name) in enumerate(contracts):
        if skip and i in skip:
            continue
        if creation_code:
            # a deploying row executes creation code too — the
            # runtime-only proof does not cover it
            continue
        norm = code[2:] if code.startswith("0x") else code
        if len(norm) < 4:
            continue
        t0 = time.perf_counter()
        try:
            summary = summary_for(norm)
            if summary.static_answerable:
                out[i] = _static_answer_result(
                    name, summary, time.perf_counter() - t0
                )
                counter.inc()
        except Exception:
            log.debug(
                "static triage failed for %s; full path", name,
                exc_info=True,
            )
    if out:
        log.info(
            "Static triage answered %d/%d contract(s) without "
            "dispatch",
            len(out),
            len(contracts),
        )
    return out


def _store_hit_result(name: str, entry, wall_s: float) -> Dict:
    """The result slot for an exact verdict-store hit: the banked
    issue set IS the analysis — no device wave, no host walk, no
    solver. Same shape as an analyzed result; the `store_hit` flag
    routes it in the routing feature log and the report meta."""
    return {
        "name": name,
        "issues": entry.issues,
        "states": 0,
        "device_prepass": None,
        "phases": {},
        "precovered_skips": 0,
        "wall_s": round(wall_s, 6),
        "error": None,
        "store_hit": True,
        "store": {
            "code_hash": entry.code_hash,
            "config_fingerprint": entry.config_fp,
            "provenance": entry.provenance,
        },
    }


def _store_triage(
    contracts: List[Tuple[str, str, str]],
    vstore,
    config_fp: str,
    linkset=None,
) -> Tuple[Dict[int, Dict], Dict[int, object]]:
    """({index: exact-hit result}, {index: IncrementalPlan}) from the
    verdict store (mythril_tpu/store). Runs BEFORE the static triage
    and the device prepass, so hit contracts never occupy a lane and
    incremental contracts explore only their changed selectors. Every
    doubt bails that contract to the full path — a store problem can
    cost speed, never correctness.

    With a corpus `linkset`, an exact codehash hit is additionally
    checked against its stored CALL-GRAPH fingerprints: byte-identical
    code whose resolved callee closure moved (implementation upgrade
    behind an unchanged proxy) is NOT served the stale verdict — it
    downgrades to a linked incremental plan re-analyzing only the
    selectors whose closure changed, or to full analysis when the
    linked diff cannot be trusted (link-unresolved / link-cycle)."""
    answers: Dict[int, Dict] = {}
    plans: Dict[int, object] = {}
    if vstore is None:
        return answers, plans
    from mythril_tpu.analysis.static import (
        static_prune_enabled,
        summary_for,
    )
    from mythril_tpu.store import (
        IncrementalBail,
        code_hash_hex,
        plan_incremental,
        plan_linked_incremental,
    )

    for i, (code, creation_code, name) in enumerate(contracts):
        if creation_code:
            # a deploying row executes creation code too — the
            # runtime-keyed verdict does not cover it
            continue
        norm = code[2:] if code.startswith("0x") else code
        if len(norm) < 8:
            continue
        t0 = time.perf_counter()
        code_hash = code_hash_hex(norm)
        try:
            entry = vstore.get(code_hash, config_fp)
        except Exception:
            log.debug("store lookup failed for %s", name, exc_info=True)
            continue
        if entry is not None:
            if linkset is not None and entry.linked_fingerprints:
                verdict = _linked_hit_verdict(
                    norm, name, entry, linkset, config_fp,
                    plan_linked_incremental, summary_for,
                )
                if verdict == "stale":
                    continue  # full analysis; serving the hit is wrong
                if verdict is not None:
                    plans[i] = verdict
                    continue
            answers[i] = _store_hit_result(
                name, entry, time.perf_counter() - t0
            )
            continue
        if not static_prune_enabled():
            continue  # the diff needs the static layer's fingerprints
        try:
            summary = summary_for(norm, config_fp=config_fp)
            nearest = vstore.nearest(
                config_fp,
                summary.function_fingerprints,
                exclude_code_hash=code_hash,
            )
            if nearest is None:
                continue
            plans[i] = plan_incremental(summary, nearest)
            log.info(
                "Store incremental plan for %s: %d changed / %d "
                "unchanged selector(s), %d banked issue(s)",
                name,
                len(plans[i].changed),
                len(plans[i].unchanged),
                len(plans[i].banked_issues),
            )
        except IncrementalBail as bail:
            log.info(
                "Store incremental bail for %s: %s (full analysis)",
                name,
                bail.reason,
            )
        except Exception:
            log.debug(
                "store incremental planning failed for %s", name,
                exc_info=True,
            )
    if answers:
        log.info(
            "Verdict store settled %d/%d contract(s) at admission",
            len(answers),
            len(contracts),
        )
    return answers, plans


def _linked_hit_verdict(
    norm: str,
    name: str,
    entry,
    linkset,
    config_fp: str,
    plan_linked_incremental,
    summary_for,
):
    """Check an exact store hit against its call-graph fingerprints.
    Returns None (hit stands), an IncrementalPlan (only the selectors
    whose callee closure moved re-run; the rest is banked), or the
    sentinel "stale" (closure moved but the diff cannot be trusted —
    full analysis, never the stale verdict)."""
    from mythril_tpu.store import IncrementalBail

    try:
        summary = summary_for(norm, config_fp=config_fp)
    except Exception:
        log.debug("summary failed for linked hit %s", name, exc_info=True)
        return None
    if summary.code_hash not in linkset.nodes:
        return None  # row not linked: pre-link behavior
    linked_now, problems = linkset.linked_fingerprints(summary.code_hash)
    if linked_now == entry.linked_fingerprints and not problems:
        return None  # closure identical everywhere
    try:
        plan = plan_linked_incremental(
            summary, entry, linked_now, problems
        )
    except IncrementalBail as bail:
        log.info(
            "Linked store hit for %s cannot be diffed: %s "
            "(full analysis)",
            name,
            bail.reason,
        )
        return "stale"
    except Exception:
        log.debug(
            "linked incremental planning failed for %s", name,
            exc_info=True,
        )
        return "stale"
    if plan is None:
        return None
    log.info(
        "Linked store hit for %s: callee closure moved for %d "
        "selector(s); %d banked",
        name,
        len(plan.changed),
        len(plan.unchanged),
    )
    return plan


def _apply_incremental(result: Optional[Dict], plan) -> Optional[Dict]:
    """Fold one incremental plan's banked issues into the fresh
    (changed-selector-restricted) result and flag the route."""
    if result is None or result.get("error"):
        return result
    from mythril_tpu.store import merge_banked_issues

    added = merge_banked_issues(result.setdefault("issues", []), plan.banked_issues)
    result["store_incremental"] = True
    result["store"] = dict(plan.as_dict(), banked_merged=added)
    return result


def _store_writeback(
    results: List[Optional[Dict]],
    contracts: List[Tuple[str, str, str]],
    prepass: Dict[int, Dict],
    vstore,
    config_fp: str,
    linkset=None,
) -> int:
    """Tier 3: persist every COMPLETE full analysis (including
    incremental ones — a fork's merged verdict is a first-class entry
    for the next fork). Store-hit and statically-answered rows are not
    re-written (their verdicts are already cheap or present); partial,
    skipped, and errored rows never are."""
    if vstore is None:
        return 0
    from mythril_tpu.analysis.static import (
        static_prune_enabled,
        summary_for,
    )
    from mythril_tpu.store import (
        banks_from_outcome,
        code_hash_hex,
        provenance,
        static_export,
    )

    written = 0
    for i, (code, creation_code, name) in enumerate(contracts):
        result = results[i] if i < len(results) else None
        if (
            result is None
            or creation_code
            or not result.get("complete")
            or result.get("store_hit")
            or result.get("static_answered")
            or result.get("skipped")
        ):
            continue
        norm = code[2:] if code.startswith("0x") else code
        if len(norm) < 8:
            continue
        summary = None
        if static_prune_enabled():
            try:
                summary = summary_for(norm, config_fp=config_fp)
            except Exception:
                summary = None
        try:
            path = vstore.put(
                code_hash_hex(norm),
                config_fp,
                issues=result.get("issues") or [],
                static=static_export(summary, linkset=linkset),
                banks=banks_from_outcome(prepass.get(i)),
                provenance=provenance(
                    wall_s=result.get("wall_s"),
                    computed_by="corpus",
                    incremental=bool(result.get("store_incremental")),
                ),
            )
            written += bool(path)
        except Exception:
            log.debug("store write-back failed for %s", name,
                      exc_info=True)
    if written:
        log.info("Verdict store banked %d verdict(s)", written)
    return written


def _skipped_result(name: str, reason: str) -> Dict:
    """The result slot for a contract the supervisor never analyzed
    (deadline expiry / SIGTERM): same shape as an analyzed result so
    report builders need no special case, explicitly marked so the
    partial report can say WHICH contracts are missing and why. The
    post-merge still folds in any witnesses the device prepass banked
    for it — a run killed at minute 10 keeps every finding harvested
    so far."""
    from mythril_tpu.support.resilience import (
        DegradationLog,
        DegradationReason,
    )

    DegradationLog().record(
        DegradationReason.CONTRACT_SKIPPED,
        site="corpus",
        detail=reason,
        contract=name,
    )
    return {
        "name": name,
        "issues": [],
        "states": 0,
        "device_prepass": None,
        "phases": {},
        "precovered_skips": 0,
        "error": None,
        "skipped": reason,
    }


def _analyze_one(payload: Tuple) -> Dict:
    """Worker: analyze one contract, return issue dicts (run in a
    spawned process; heavyweight imports stay inside). The result
    carries its own wall (`wall_s`) — the per-contract outcome field
    the routing feature log (observe/routing.py) trains on."""
    t_start = time.perf_counter()
    (
        code,
        creation_code,
        name,
        address,
        strategy,
        transaction_count,
        execution_timeout,
        create_timeout,
        max_depth,
        loop_bound,
        modules,
        solver_timeout,
        use_device,
        prepass_outcome,
        deterministic_solving,
    ) = payload
    args = restore_device_args = restore_deterministic = None
    try:
        from mythril_tpu.analysis.security import fire_lasers
        from mythril_tpu.analysis.symbolic import SymExecWrapper
        from mythril_tpu.ethereum.evmcontract import EVMContract
        from mythril_tpu.support.support_args import args

        if solver_timeout:
            args.solver_timeout = solver_timeout
        if deterministic_solving is not None:
            # threaded through the payload (not toggled by the caller
            # around the whole run) so the flag flip is scoped to this
            # one analysis and restored on every exit path
            restore_deterministic = args.deterministic_solving
            args.deterministic_solving = deterministic_solving
        if not use_device:
            # pooled workers must not contend for the one accelerator;
            # any prepass outcome arrives via the payload (injected) or
            # the post-pool witness merge — device paths stay parent-only.
            # Restored on exit: host-only corpus legs can run in-parent
            # (single process) and must not degrade later analyses in
            # the same process through the shared Args singleton.
            restore_device_args = (args.device_prepass, args.device_solving)
            args.device_prepass = "never"
            args.device_solving = "never"

        from mythril_tpu.observe.spans import trace

        contract = EVMContract(
            code=code or "", creation_code=creation_code or "", name=name
        )
        with trace("contract.analyze", contract=name):
            sym = SymExecWrapper(
                contract,
                address,
                strategy,
                max_depth=max_depth,
                execution_timeout=execution_timeout,
                loop_bound=loop_bound,
                create_timeout=create_timeout,
                transaction_count=transaction_count,
                modules=modules,
                compulsory_statespace=False,
                prepass_outcome=prepass_outcome,
            )
            issues = fire_lasers(sym, modules)
        exploration = getattr(sym, "device_exploration", None)
        from mythril_tpu.support.phase_profile import PhaseProfile

        return {
            "name": name,
            "issues": [issue.as_dict for issue in issues],
            "states": sym.laser.total_states,
            "device_prepass": exploration["stats"] if exploration else None,
            "phases": PhaseProfile().as_dict(),
            "precovered_skips": sym.laser.device_precovered_skips,
            "wall_s": round(time.perf_counter() - t_start, 3),
            "error": None,
        }
    except Exception:
        return {
            "name": name,
            "issues": [],
            "states": 0,
            "wall_s": round(time.perf_counter() - t_start, 3),
            "error": traceback.format_exc(),
        }
    finally:
        if restore_device_args is not None and args is not None:
            args.device_prepass, args.device_solving = restore_device_args
        if restore_deterministic is not None and args is not None:
            args.deterministic_solving = restore_deterministic


#: public name for the pooled-mode worker: the analysis service
#: (mythril_tpu/service/engine.py) feeds finished device stripes
#: through the exact per-contract pipeline the corpus pool runs, so
#: the payload contract is shared, not duplicated
analyze_one_payload = _analyze_one


def analyze_corpus(
    contracts: List[Tuple[str, str, str]],
    address: int = 0x901D573B8CE8C997DE5F19173C32D966B4Fa55FE,
    strategy: str = "bfs",
    transaction_count: int = 2,
    execution_timeout: int = 60,
    create_timeout: int = 10,
    max_depth: int = 128,
    loop_bound: int = 3,
    modules: Optional[List[str]] = None,
    solver_timeout: Optional[int] = None,
    processes: Optional[int] = None,
    use_device: Optional[bool] = None,
    device_budget_s: Optional[float] = None,
    deterministic_solving: Optional[bool] = None,
    deadline_s: Optional[float] = None,
    on_timeout: str = "partial",
    devices: Optional[int] = None,
    store_dir: Optional[str] = None,
    store: Optional[bool] = None,
    router_dir: Optional[str] = None,
    router: Optional[bool] = None,
    _flag_scoped: bool = False,
) -> List[Dict]:
    """Analyze `contracts` = [(runtime_code_hex, creation_code_hex,
    name), ...]: one striped device prepass in this process plus the
    per-contract host pipeline — sequential with outcome injection when
    single-process, overlapped with a worker pool (witnesses merged
    afterward) otherwise. Returns one result dict per contract
    ({name, issues, error, device_prepass, phases, complete}).

    Resource exhaustion is an OUTCOME here, not a crash: the supervisor
    (support/resilience.py) is consulted at every contract boundary.
    With `deadline_s` (falling back to the process-global run deadline)
    an expired budget — or a delivered SIGINT/SIGTERM — stops launching
    new work; already-harvested device witnesses still merge into the
    skipped contracts' slots, each result says whether it is
    `complete`, and `on_timeout` picks between the partial result list
    (default) and a DeadlineExpiredError. Signal handling is the
    CALLER's choice: enter `resilience.graceful_shutdown()` around this
    call (the CLI and the fault harness do) to convert SIGINT/SIGTERM
    into the graceful partial-run stop instead of process death."""
    from mythril_tpu.support import resilience

    processes = processes or min(len(contracts), _effective_cpus())
    deadline = (
        resilience.run_deadline()
        if deadline_s is None
        else resilience.Deadline(deadline_s, label="corpus")
    )
    if deterministic_solving is not None and not _flag_scoped:
        # The flag must also govern the PARENT-side device prepass
        # (flip solving + witness banking run in this process, not in
        # _analyze_one), so it is scoped to this call with a restore on
        # every exit path. Spawned workers (fresh processes, default
        # Args) still get it via the payload, hence the parameter is
        # threaded through the recursion too.
        from mythril_tpu.support.support_args import args as _args

        _restore_det = _args.deterministic_solving
        _args.deterministic_solving = deterministic_solving
        try:
            return analyze_corpus(
                contracts,
                address=address,
                strategy=strategy,
                transaction_count=transaction_count,
                execution_timeout=execution_timeout,
                create_timeout=create_timeout,
                max_depth=max_depth,
                loop_bound=loop_bound,
                modules=modules,
                solver_timeout=solver_timeout,
                processes=processes,
                use_device=use_device,
                device_budget_s=device_budget_s,
                deterministic_solving=deterministic_solving,
                deadline_s=deadline_s,
                on_timeout=on_timeout,
                devices=devices,
                store_dir=store_dir,
                store=store,
                router_dir=router_dir,
                router=router,
                _flag_scoped=True,
            )
        finally:
            _args.deterministic_solving = _restore_det
    if use_device is None:
        # the device axis is on whenever an accelerator is present —
        # the PARENT owns the chip, so pooling does not disable it
        from mythril_tpu.support.accel import accelerator_present

        use_device = accelerator_present()

    # tier 1+2 of the verdict store (mythril_tpu/store): exact
    # (codehash, config-fingerprint) hits settle HERE in microseconds
    # with the banked issue set; near-duplicates get an incremental
    # plan that masks their unchanged selectors out of the device
    # exploration and pre-banks the untouched functions' issues
    from mythril_tpu.analysis.static import (
        static_answer_enabled,
        static_prune_enabled,
    )
    from mythril_tpu.analysis.static.summary import (
        analysis_config_fingerprint,
    )

    config_fp = analysis_config_fingerprint(
        modules=modules,
        transaction_count=transaction_count,
        solver_timeout=solver_timeout,
        create_timeout=create_timeout,
    )
    # corpus-mode cross-contract linking (analysis/static/linkset.py),
    # BEFORE the store triage and the prepass: the resolved call graph
    # feeds (a) the linked-fingerprint diff that catches "same proxy
    # bytes, upgraded implementation" exact hits, (b) per-result link
    # meta in the jsonv2 report, (c) routing-log v4 features
    linkset = None
    if static_prune_enabled() and contracts:
        try:
            from mythril_tpu.analysis.static import link_corpus

            linkset = link_corpus(contracts)
            link_stats = linkset.stats()
            log.info(
                "Link pass: %d node(s), %d/%d edge(s) resolved, "
                "%d proxy pair(s) in %.1fms",
                link_stats["nodes"],
                link_stats["edges_resolved"],
                link_stats["edges"],
                link_stats["proxy_pairs"],
                link_stats["wall_ms"],
            )
        except Exception:
            linkset = None
            log.debug("corpus link pass failed", exc_info=True)
    vstore = None
    if store is not False:
        try:
            from mythril_tpu.store import configured_store

            vstore = configured_store(store_dir)
        except Exception:
            log.debug("verdict store unavailable", exc_info=True)
    store_answers, store_plans = _store_triage(
        contracts, vstore, config_fp, linkset=linkset
    )
    selector_masks = {
        i: (plan.mask_selectors, plan.mask_directions)
        for i, plan in store_plans.items()
    } or None

    # the static-answer triage tier: contracts the semantic screen
    # settles are answered HERE (microseconds) and excluded from the
    # device prepass — the prepass sees their rows as non-runnable so
    # the index mapping every consumer shares stays intact
    static_answers: Dict[int, Dict] = (
        _static_triage(contracts, skip=frozenset(store_answers))
        if static_answer_enabled()
        else {}
    )
    prepass_rows = list(contracts)
    for i in list(static_answers) + list(store_answers):
        prepass_rows[i] = ("", contracts[i][1], contracts[i][2])

    # The learned tier-ladder router (mythril_tpu/routing): for every
    # contract the triage tiers did NOT settle, price host-walk vs
    # device-waves from the routing features and keep host-routed rows
    # OUT of the device prepass — the prepass budget scales with the
    # RUNNABLE row count, so cheap contracts the walk converges on in
    # milliseconds stop billing device waves. Router absent / refused
    # / --no-router: the plan stays empty and this whole block is a
    # no-op — today's routes, bit for bit. Mis-routes are repaired
    # in-flight by _promote_overruns below.
    route_plan: Dict[int, str] = {}
    route_decisions: Dict[int, object] = {}
    corpus_router = None
    if router is not False and use_device:
        try:
            from mythril_tpu.routing import router as _routing_rt

            corpus_router = (
                _routing_rt.load_router(router_dir)
                if router_dir
                else _routing_rt.configured_router()
            )
        except Exception:
            corpus_router = None
            log.debug("router load failed", exc_info=True)
    if corpus_router is not None:
        from mythril_tpu import observe as _obs

        for i, (code, _creation, _name) in enumerate(contracts):
            if i in static_answers or i in store_answers:
                continue
            code_norm = code[2:] if code.startswith("0x") else code
            if len(code_norm) < 8:
                continue  # not a runnable prepass row anyway
            try:
                link_meta = None
                if linkset is not None:
                    import hashlib as _hl

                    link_meta = linkset.node_meta(
                        "0x" + _hl.sha256(
                            bytes.fromhex(code_norm)
                        ).hexdigest()
                    )
                decision = corpus_router.decide(
                    _obs.routing_features_for(code, link=link_meta),
                    tiers=["host-walk", "device-waves"],
                )
            except Exception:
                log.debug("route decision failed", exc_info=True)
                continue
            if decision is None:
                continue
            route_plan[i] = decision.route
            route_decisions[i] = decision
            if decision.route == "host-walk":
                prepass_rows[i] = ("", contracts[i][1], contracts[i][2])
        if route_plan:
            log.info(
                "Router v%d: %d host-walk / %d device-waves of %d "
                "routable contract(s)",
                corpus_router.version,
                sum(1 for r in route_plan.values() if r == "host-walk"),
                sum(1 for r in route_plan.values() if r == "device-waves"),
                len(route_plan),
            )

    single_process = processes <= 1 or len(contracts) == 1

    def payload(code, creation_code, name, worker_device, outcome):
        return (
            code,
            creation_code,
            name,
            address,
            strategy,
            transaction_count,
            execution_timeout,
            create_timeout,
            max_depth,
            loop_bound,
            modules,
            solver_timeout,
            worker_device,
            outcome,
            deterministic_solving,
        )

    prepass: Dict[str, Dict] = {}
    if single_process:
        # Sequential hosts: the striped device prepass OVERLAPS the
        # per-contract analyses — a prepass thread runs the waves (pure
        # device work) while the main thread analyzes, and both sides
        # take HOST_SYMBOLIC_LOCK around host symbolic state (the term
        # arena and the incremental CDCL session are process-global —
        # support/host_lock.py). Contracts reached after the prepass
        # lands get its outcome injected (witness issues,
        # coverage-guided pruning); earlier ones pick up their
        # witnesses in the post-merge, same as the pooled path.
        # Overlap needs either a second core or a corpus long enough
        # to amortize the tax: a wave's host-side dispatch/sync work
        # contends with the analyses on a 1-core box (measured: a
        # budget-bound contract analyzed beside a live prepass thread
        # loses ~30% of its explored states on a 13-fixture corpus),
        # but the waves are device-bound (~2.7s of GIL-held work per
        # ~33s wave at corpus sizes), so from OVERLAP_MIN_CORPUS
        # contracts the chip rides along ~free while the CPU
        # analyzes. Below that, single-core hosts — and lone
        # contracts, which have nothing to overlap with — run the
        # prepass FIRST, uncontended, then analyze with the final
        # outcome injected.
        if use_device and len(contracts) > 1 and (
            _effective_cpus() > 1
            or len(_runnable_rows(prepass_rows)) >= OVERLAP_MIN_CORPUS
        ):
            pre = OverlappedPrepass(
                prepass_rows,
                address,
                transaction_count,
                device_budget_s,
                execution_timeout=execution_timeout,
                ownership=_ownership_enabled(use_device),
                deadline=deadline,
                mesh_groups=devices,
                selector_masks=selector_masks,
            )
            # Smallest code first: cheap analyses (which converge well
            # inside their budgets regardless of contention) soak up
            # the prepass's busy window, so the budget-bound
            # heavyweights run after it finishes — on an uncontended
            # core and with the FINAL prepass outcome instead of a
            # partial. Measured on the 13-fixture corpus (1-core box):
            # scheduling the largest contract first instead cost it
            # ~30% of its explored states to prepass-thread contention.
            order = sorted(
                range(len(contracts)), key=lambda i: len(contracts[i][0])
            )
            # Overlap window: cheap analyses share the (single) core
            # with the prepass for about its active budget, then one
            # drain lets it finish uncontended. Past the window every
            # remaining contract runs on a quiet core — measured: a
            # budget-bound contract analyzed beside a live prepass
            # thread loses ~30% of its explored states to contention.
            # Sized from the RUNNABLE count (the same filter
            # corpus_device_prepass applies) so rows with no runtime
            # code don't inflate the contended period. Large corpora
            # get a 2x window: their waves bill active time at nearly
            # wall rate (flip bursts wait for the lock at most once
            # per wave), so by 2x the budget the prepass has finished
            # on its own and the drain is a no-op instead of a
            # main-thread stall on pure device work.
            n_run = max(1, len(_runnable_rows(prepass_rows)))
            overlap_window_s = (
                2.0 if n_run >= OVERLAP_MIN_CORPUS else 1.25
            ) * resolve_prepass_budget_s(
                n_run,
                device_budget_s,
                execution_timeout=execution_timeout,
                ownership=_ownership_enabled(use_device),
            )
            t_overlap = time.perf_counter()
            own = _ownership_enabled(use_device)
            slots: List[Optional[Dict]] = [None] * len(contracts)
            halt_reason: Optional[str] = None
            try:
                # Ownership-aware scheduling: a contract the running
                # prepass may still freeze as final (no hard gate
                # failure published yet) is DEFERRED rather than
                # walked — walking it now would burn its full budget
                # on work the chip is about to hand over. Clearly
                # unownable contracts (degraded, overflowed) walk
                # immediately and soak the overlap window; once the
                # prepass ends (or the window drains it), everything
                # left resolves against final outcomes.
                pending = list(order)
                while pending:
                    progressed = False
                    deferred: List[int] = []
                    for i in pending:
                        # the supervisor boundary: an expired deadline
                        # or a delivered signal stops LAUNCHING work;
                        # everything already harvested keeps flowing
                        # into the partial report below
                        resilience.inject("corpus.contract")
                        if halt_reason is None:
                            halt_reason = resilience.interrupted_reason(
                                deadline
                            )
                        code, creation_code, name = contracts[i]
                        if i in store_answers:
                            # exact store hit: the banked verdict is
                            # the analysis — survives a deadline halt
                            # like the static answers below
                            slots[i] = store_answers[i]
                            progressed = True
                            continue
                        if i in static_answers:
                            # statically answered: the empty issue set
                            # is the analysis — it even survives a
                            # deadline halt (it costs microseconds)
                            slots[i] = static_answers[i]
                            progressed = True
                            continue
                        if halt_reason is not None:
                            slots[i] = _skipped_result(name, halt_reason)
                            progressed = True
                            continue
                        # per-contract, as before the deferral rework:
                        # a long pass over `pending` must still hand
                        # the prepass its uncontended tail past the
                        # overlap window
                        if time.perf_counter() - t_overlap > overlap_window_s:
                            pre.drain()
                        outcome, device_ok = pre.outcome_for(i)
                        if outcome is None and i in store_plans:
                            # no device outcome (yet): the store's
                            # banked coverage for the unchanged
                            # selectors pre-empts walk feasibility
                            # queries instead
                            outcome = store_plans[i].injected_outcome
                        if own and _outcome_owns(outcome):
                            # device-complete contract: evidence IS
                            # the analysis; no walk, no lock, no
                            # solver
                            owned_res = _owned_result(
                                code, creation_code, name, outcome,
                                address,
                            )
                            if owned_res is not None:
                                if i in store_plans:
                                    owned_res = _apply_incremental(
                                        owned_res, store_plans[i]
                                    )
                                slots[i] = owned_res
                                progressed = True
                                continue
                        if (
                            not device_ok
                            and own
                            and _maybe_ownable(outcome)
                            and not pre.drain_abandoned
                        ):
                            # a hung prepass (abandoned drain) will
                            # never publish finality: deferring past it
                            # would spin this loop forever
                            deferred.append(i)
                            continue
                        with pre.lock:
                            slots[i] = _analyze_one(
                                payload(
                                    code,
                                    creation_code,
                                    name,
                                    use_device and device_ok,
                                    outcome,
                                )
                            )
                        if i in store_plans:
                            slots[i] = _apply_incremental(
                                slots[i], store_plans[i]
                            )
                        pre.yield_lock()
                        progressed = True
                    pending = deferred
                    if pending and not progressed:
                        # only deferred work left: let the prepass run
                        # uncontended and poll its published finality
                        time.sleep(1.0)
                results = slots
            finally:
                # an exception (including a caller's alarm/deadline)
                # must not orphan the prepass thread mid-wave: it would
                # keep the chip and the host lock busy under whatever
                # the caller measures next
                prepass = pre.finish()
        else:
            if use_device:
                prepass = corpus_device_prepass(
                    prepass_rows,
                    budget_s=device_budget_s,
                    address=address,
                    transaction_count=transaction_count,
                    execution_timeout=execution_timeout,
                    ownership=_ownership_enabled(use_device),
                    deadline=deadline,
                    stop_event=resilience.shutdown_event(),
                    mesh_groups=devices,
                    selector_masks=selector_masks,
                )
            own = _ownership_enabled(use_device)
            results = []
            halt_reason = None
            for i, (code, creation_code, name) in enumerate(contracts):
                resilience.inject("corpus.contract")
                if halt_reason is None:
                    halt_reason = resilience.interrupted_reason(deadline)
                if i in store_answers:
                    results.append(store_answers[i])
                    continue
                if i in static_answers:
                    results.append(static_answers[i])
                    continue
                if halt_reason is not None:
                    # device-owned evidence survives the halt: synthesis
                    # is cheap (no walk, no solver), so an owned
                    # contract still reports in full
                    owned_res = (
                        _owned_result(
                            code, creation_code, name, prepass[i], address
                        )
                        if own and _outcome_owns(prepass.get(i))
                        else None
                    )
                    results.append(
                        owned_res
                        if owned_res is not None
                        else _skipped_result(name, halt_reason)
                    )
                    continue
                owned_res = (
                    _owned_result(
                        code, creation_code, name, prepass[i], address
                    )
                    if own and _outcome_owns(prepass.get(i))
                    else None
                )
                if owned_res is None:
                    outcome = prepass.get(i)
                    if outcome is None and i in store_plans:
                        outcome = store_plans[i].injected_outcome
                    owned_res = _analyze_one(
                        payload(
                            code,
                            creation_code,
                            name,
                            use_device,
                            outcome,
                        )
                    )
                if i in store_plans:
                    owned_res = _apply_incremental(
                        owned_res, store_plans[i]
                    )
                results.append(owned_res)
    else:
        # pooled hosts: the prepass likewise overlaps the worker pool;
        # witnesses merge in when both finish. Results are collected
        # INCREMENTALLY (imap preserves order) so a deadline or a
        # signal keeps everything finished so far and marks only the
        # tail skipped — map_async's all-or-nothing get() would lose
        # the whole pool on a timeout.
        payloads = [
            payload(
                code,
                creation_code,
                name,
                False,
                (
                    store_plans[i].injected_outcome
                    if i in store_plans
                    else None
                ),
            )
            for i, (code, creation_code, name) in enumerate(contracts)
            if i not in static_answers and i not in store_answers
        ]
        ctx = mp.get_context("spawn")  # fresh singletons per worker
        with ctx.Pool(processes=processes) as pool:
            walked = pool.imap(_analyze_one, payloads)
            if use_device:
                prepass = corpus_device_prepass(
                    prepass_rows,
                    budget_s=device_budget_s,
                    address=address,
                    transaction_count=transaction_count,
                    deadline=deadline,
                    stop_event=resilience.shutdown_event(),
                    mesh_groups=devices,
                    selector_masks=selector_masks,
                )
            results = []
            halt_reason = None
            for i, (code, _creation, name) in enumerate(contracts):
                if i in store_answers:
                    results.append(store_answers[i])
                    continue
                if i in static_answers:
                    results.append(static_answers[i])
                    continue
                if halt_reason is None:
                    halt_reason = resilience.interrupted_reason(deadline)
                if halt_reason is None:
                    try:
                        walked_res = (
                            walked.next()
                            if deadline is None
                            else walked.next(max(0.1, deadline.remaining))
                        )
                        if i in store_plans:
                            walked_res = _apply_incremental(
                                walked_res, store_plans[i]
                            )
                        results.append(walked_res)
                        continue
                    except mp.TimeoutError:
                        halt_reason = (
                            resilience.interrupted_reason(deadline)
                            or "deadline-expired"
                        )
                results.append(_skipped_result(name, halt_reason))
            if halt_reason is not None:
                # in-flight workers past the deadline: stop them now
                pool.terminate()
    if prepass:
        _merge_prepass_witnesses(results, contracts, prepass, address)
    if route_plan:
        _promote_overruns(
            results,
            contracts,
            route_plan,
            route_decisions,
            corpus_router,
            address=address,
            transaction_count=transaction_count,
            execution_timeout=execution_timeout,
            use_device=use_device,
            devices=devices,
            deadline=deadline,
        )
    try:
        # one saturation sample at the run boundary: batch runs get
        # the same mtpu_device_* gauges the serve sampler keeps live
        from mythril_tpu import observe as _observe

        _observe.device_monitor().sample()
    except Exception:
        log.debug("device monitor sample failed", exc_info=True)
    skipped = 0
    for result in results:
        if result is None:
            continue
        # per-contract completion status, first-class in the result
        # (and from there in the json/jsonv2 report meta): a partial
        # run SAYS which contracts it covered
        result["complete"] = (
            not result.get("skipped") and result.get("error") is None
        )
        skipped += bool(result.get("skipped"))
    # tier 3: every completed full analysis becomes a store entry —
    # the write that turns this run's compute into the next run's
    # admission-time answer
    if vstore is not None:
        _store_writeback(
            results, contracts, prepass, vstore, config_fp,
            linkset=linkset,
        )
    if linkset is not None:
        _attach_link_meta(results, contracts, linkset)
    # router decisions feed their own training data (satellite 2):
    # planned rows settle as routed-<tier> / promoted-<tier> in the
    # routing JSONL. Stamped AFTER the store writeback so banked
    # verdicts stay route-free (a store hit replays as store-hit).
    for i, planned in route_plan.items():
        result = results[i] if i < len(results) else None
        if result is None or result.get("skipped") or result.get("promoted"):
            continue
        result["routed"] = planned
    _emit_routing_records(results, contracts, linkset=linkset)
    if skipped and on_timeout == "fail":
        from mythril_tpu.exceptions import DeadlineExpiredError

        raise DeadlineExpiredError(
            f"{skipped}/{len(contracts)} contract(s) unanalyzed at the "
            "deadline (--on-timeout=fail)"
        )
    return results


def _attach_link_meta(
    results: List[Optional[Dict]],
    contracts: List[Tuple[str, str, str]],
    linkset,
) -> None:
    """Per-result cross-contract link facts for the jsonv2 report
    meta (and anyone reading the raw result dicts): the compact node
    block plus the corpus-level stats on every row — consumers of one
    contract's report still see the resolve rate the graph achieved."""
    run_stats = None
    try:
        run_stats = linkset.stats()
    except Exception:
        log.debug("link stats failed", exc_info=True)
    import hashlib as _hashlib

    for (code, _creation, _name), result in zip(contracts, results):
        if result is None:
            continue
        try:
            norm = code[2:] if code.startswith("0x") else code
            code_hash = (
                "0x" + _hashlib.sha256(bytes.fromhex(norm)).hexdigest()
            )
        except ValueError:
            continue
        meta = linkset.node_meta(code_hash)
        if meta is None:
            continue
        result["link"] = meta
        if run_stats is not None:
            result["link_run"] = dict(run_stats)


def _emit_routing_records(
    results: List[Dict],
    contracts: List[Tuple[str, str, str]],
    linkset=None,
) -> None:
    """One routing-feature record per analyzed contract
    (observe/routing.py): static features joined with the route taken
    and the outcome — the JSONL training set ROADMAP item 5's cost
    model needs. Never fatal; a record failure loses one row, not the
    run."""
    from mythril_tpu import observe

    if not observe.enabled():
        return
    import hashlib

    for (code, _creation, name), result in zip(contracts, results):
        if result is None:
            continue
        try:
            code_norm = code[2:] if code.startswith("0x") else code
            try:
                digest = hashlib.sha256(
                    bytes.fromhex(code_norm or "")
                ).hexdigest()
            except ValueError:
                digest = ""
            outcome = observe.routing_outcome_for(result)
            # every record gets a journey skeleton: corpus analyses
            # have no HTTP job id, so the id is minted here and the
            # route lands as the timeline's middle tier — the same
            # features ⨝ route ⨝ outcome ⨝ timeline join key the
            # service emits (observe/journey.py)
            journey_id = observe.new_journey_id()
            observe.journey_event(
                journey_id, "admission", "corpus", contract=name,
            )
            observe.journey_event(
                journey_id, outcome.get("route", "?"), "routed",
                wall_s=outcome.get("wall_s"),
            )
            observe.journey_event(
                journey_id, "settle",
                "done" if not outcome.get("error") else "failed",
                issues=outcome.get("issues"),
            )
            link_meta = None
            if linkset is not None:
                try:
                    link_meta = linkset.node_meta("0x" + digest)
                except Exception:
                    link_meta = None
            observe.routing_log().record(
                contract=name,
                code_hash=digest,
                features=observe.routing_features_for(
                    code_norm, link=link_meta
                ),
                outcome=outcome,
                journey_id=journey_id,
            )
        except Exception:
            log.debug("routing record failed for %s", name, exc_info=True)


def _promote_overruns(
    results: List[Optional[Dict]],
    contracts: List[Tuple[str, str, str]],
    route_plan: Dict[int, str],
    route_decisions: Dict[int, object],
    corpus_router,
    address: int,
    transaction_count: int,
    execution_timeout: int,
    use_device: bool,
    devices: Optional[int],
    deadline,
) -> None:
    """The router's in-flight repair tier: a host-routed contract
    whose walk errored or overran the decision's predicted budget
    (`RouteDecision.budget_s` — slack times the predicted wall) was
    mis-routed, so it gets the device waves it was denied: one small
    prepass over just the overrun rows, witnesses merged in place, the
    result stamped ``promoted`` (the routing record settles as
    ``promoted-device-waves``, its own outcome class, so the trainer
    prices the mis-route). Regret — wall actually burnt beyond the
    budget — feeds mtpu_router_regret_seconds_total."""
    from mythril_tpu.support import resilience

    if not use_device or resilience.interrupted_reason(deadline) is not None:
        return
    overrun: List[int] = []
    for i, planned in route_plan.items():
        if planned != "host-walk":
            continue
        result = results[i] if i < len(results) else None
        if result is None or result.get("skipped"):
            continue
        decision = route_decisions.get(i)
        budget = decision.budget_s() if decision is not None else 0.0
        wall = result.get("wall_s") or 0.0
        if result.get("error") is not None or (budget and wall > budget):
            overrun.append(i)
            if corpus_router is not None and budget and wall > budget:
                corpus_router.note_regret(wall - budget)
    if not overrun:
        return
    promo_rows: List[Tuple[str, str, str]] = [
        (
            contracts[i][0] if i in overrun else "",
            contracts[i][1],
            contracts[i][2],
        )
        for i in range(len(contracts))
    ]
    try:
        promo = corpus_device_prepass(
            promo_rows,
            address=address,
            transaction_count=transaction_count,
            execution_timeout=execution_timeout,
            ownership=False,
            deadline=deadline,
            stop_event=resilience.shutdown_event(),
            mesh_groups=devices,
        )
    except Exception:
        log.debug("promotion prepass failed", exc_info=True)
        return
    _merge_prepass_witnesses(results, contracts, promo, address)
    for i in overrun:
        result = results[i]
        if result is not None:
            result["promoted"] = "device-waves"
            if corpus_router is not None:
                corpus_router.note_promotion("host-walk", "device-waves")


def _merge_prepass_witnesses(
    results: List[Dict],
    contracts: List[Tuple[str, str, str]],
    prepass: Dict[int, Dict],
    address: int,
) -> None:
    """Fold the device prepass's banked witnesses into the pooled
    results: per contract (by position — pool.map preserves order),
    attach the prepass counters and append witness issues for
    locations no host worker reported."""
    from mythril_tpu.analysis.prepass import witness_issues
    from mythril_tpu.ethereum.evmcontract import EVMContract

    for i, (code, _creation, name) in enumerate(contracts):
        outcome = prepass.get(i)
        result = results[i] if i < len(results) else None
        if outcome is None or result is None:
            continue
        if result.get("owned"):
            continue  # issues ARE the witnesses; nothing to merge
        result["device_prepass"] = outcome["stats"]
        try:
            contract = EVMContract(code=code or "", name=name)
            fresh = witness_issues(contract, outcome, address)
        except Exception:
            log.debug("witness merge failed for %s", name, exc_info=True)
            continue
        seen = {(i.get("address"), i.get("swc-id")) for i in result["issues"]}
        extra = [
            issue.as_dict
            for issue in fresh
            if (issue.address, issue.swc_id) not in seen
        ]
        if extra:
            log.info(
                "Device prepass contributed %d issue(s) to %s that the "
                "host walk did not find",
                len(extra),
                name,
            )
            result["issues"].extend(extra)
            outcome["stats"]["witness_issues"] = len(extra)


def mesh_explore_corpus(
    contracts: List[Tuple[str, str, str]],
    n_devices: Optional[int] = None,
    lanes_per_contract: int = 16,
    max_steps: int = 2048,
    calldata_len: int = 68,
    seed: int = 7,
) -> Dict:
    """Corpus exploration sharded over a device mesh (SURVEY §2.4's
    per-contract-loop axis): every contract becomes a stripe of lanes
    with distinct calldata seeds, the whole wave is one lane-sharded
    StateBatch, and the mesh splits it over the dp axis — the batched
    replacement for the reference's sequential per-contract loop.

    Returns {lane_steps, wall_s, lane_steps_per_sec, contracts,
    lanes, coverage} — used by tools/corpus_bench.py --mesh.
    """
    import random
    import time as _time

    import numpy as np

    from mythril_tpu.laser.batch.run import run
    from mythril_tpu.laser.batch.seeds import code_cap_bucket, selector_seeds
    from mythril_tpu.laser.batch.state import make_batch, make_code_table
    from mythril_tpu.parallel import make_mesh, replicate_table, shard_batch

    rng = random.Random(seed)
    codes = []
    seeds_per_code = []
    for runtime_hex, _creation, _name in contracts:
        runtime_hex = runtime_hex[2:] if runtime_hex.startswith("0x") else runtime_hex
        codes.append(bytes.fromhex(runtime_hex))
        seeds_per_code.append(
            selector_seeds(runtime_hex, lanes_per_contract, calldata_len, rng)
        )

    cap = code_cap_bucket(max(len(c) for c in codes))
    table = make_code_table(codes, code_cap=cap)

    mesh = make_mesh(n_devices)
    n_dev = mesh.devices.size
    n_lanes = len(codes) * lanes_per_contract
    pad = (-n_lanes) % n_dev
    code_ids = np.array(
        [i for i in range(len(codes)) for _ in range(lanes_per_contract)]
        + [0] * pad,
        dtype=np.int32,
    )
    calldata = [d for seeds in seeds_per_code for d in seeds]
    calldata += [b"\x00" * calldata_len] * pad

    batch = make_batch(len(code_ids), code_ids=code_ids, calldata=calldata)
    batch = shard_batch(batch, mesh)
    table = replicate_table(table, mesh)

    # warm the jit cache with the SAME static args (max_steps is a
    # static jit argument — a different value compiles a different
    # executable) so the measurement is execution, not compile
    warm, _ = run(batch, table, max_steps=max_steps)
    np.asarray(warm.pc)[:1]

    t0 = _time.perf_counter()
    out, steps = run(batch, table, max_steps=max_steps)
    seen_host = np.asarray(out.pc_seen)  # the device->host sync point
    wall = _time.perf_counter() - t0
    covered = int(
        (np.unpackbits(seen_host.view(np.uint8), axis=-1) != 0).sum()
    )

    lane_steps = int(steps) * len(code_ids)
    return {
        "devices": int(n_dev),
        "contracts": len(codes),
        "lanes": len(code_ids),
        "steps": int(steps),
        "lane_steps": lane_steps,
        "wall_s": round(wall, 3),
        "lane_steps_per_sec": round(lane_steps / wall, 1),
        "covered_pc_bits": covered,
    }
