"""Corpus-parallel analysis: many contracts at once.

The reference analyzes contracts strictly sequentially
(mythril/mythril/mythril_analyzer.py:145-185 — a plain for-loop);
SURVEY.md §2.4 maps that loop to this framework's corpus-sharding
axis. Each worker process runs one contract through the standard
SymExecWrapper + fire_lasers pipeline with fresh singleton state, so
N workers deliver ~N× contracts/sec on the embarrassingly parallel
part of the workload.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import traceback
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)


def _analyze_one(payload: Tuple) -> Dict:
    """Worker: analyze one contract, return issue dicts (run in a
    spawned process; heavyweight imports stay inside)."""
    (
        code,
        creation_code,
        name,
        address,
        strategy,
        transaction_count,
        execution_timeout,
        create_timeout,
        max_depth,
        loop_bound,
        modules,
        solver_timeout,
        use_device,
    ) = payload
    try:
        from mythril_tpu.analysis.security import fire_lasers
        from mythril_tpu.analysis.symbolic import SymExecWrapper
        from mythril_tpu.ethereum.evmcontract import EVMContract
        from mythril_tpu.support.support_args import args

        if solver_timeout:
            args.solver_timeout = solver_timeout
        if not use_device:
            # pooled workers must not contend for the one accelerator;
            # device paths run in-parent (or single-process) only
            args.device_prepass = "never"
            args.device_solving = "never"

        contract = EVMContract(
            code=code or "", creation_code=creation_code or "", name=name
        )
        sym = SymExecWrapper(
            contract,
            address,
            strategy,
            max_depth=max_depth,
            execution_timeout=execution_timeout,
            loop_bound=loop_bound,
            create_timeout=create_timeout,
            transaction_count=transaction_count,
            modules=modules,
            compulsory_statespace=False,
        )
        issues = fire_lasers(sym, modules)
        exploration = getattr(sym, "device_exploration", None)
        return {
            "name": name,
            "issues": [issue.as_dict for issue in issues],
            "states": sym.laser.total_states,
            "device_prepass": exploration["stats"] if exploration else None,
            "error": None,
        }
    except Exception:
        return {
            "name": name,
            "issues": [],
            "states": 0,
            "error": traceback.format_exc(),
        }


def analyze_corpus(
    contracts: List[Tuple[str, str, str]],
    address: int = 0x901D573B8CE8C997DE5F19173C32D966B4Fa55FE,
    strategy: str = "bfs",
    transaction_count: int = 2,
    execution_timeout: int = 60,
    create_timeout: int = 10,
    max_depth: int = 128,
    loop_bound: int = 3,
    modules: Optional[List[str]] = None,
    solver_timeout: Optional[int] = None,
    processes: Optional[int] = None,
    use_device: Optional[bool] = None,
) -> List[Dict]:
    """Analyze `contracts` = [(runtime_code_hex, creation_code_hex,
    name), ...] across a process pool; returns one result dict per
    contract ({name, issues, error})."""
    processes = processes or min(len(contracts), mp.cpu_count())
    if use_device is None:
        use_device = processes <= 1 or len(contracts) == 1
    payloads = [
        (
            code,
            creation_code,
            name,
            address,
            strategy,
            transaction_count,
            execution_timeout,
            create_timeout,
            max_depth,
            loop_bound,
            modules,
            solver_timeout,
            use_device,
        )
        for code, creation_code, name in contracts
    ]
    if processes <= 1 or len(payloads) == 1:
        return [_analyze_one(p) for p in payloads]

    ctx = mp.get_context("spawn")  # fresh singletons per worker
    with ctx.Pool(processes=processes) as pool:
        results = pool.map(_analyze_one, payloads)
    return results
