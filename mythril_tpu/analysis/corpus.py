"""Corpus-parallel analysis: many contracts at once.

The reference analyzes contracts strictly sequentially
(mythril/mythril/mythril_analyzer.py:145-185 — a plain for-loop);
SURVEY.md §2.4 maps that loop to this framework's corpus-sharding
axis. Each worker process runs one contract through the standard
SymExecWrapper + fire_lasers pipeline with fresh singleton state, so
N workers deliver ~N× contracts/sec on the embarrassingly parallel
part of the workload.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import traceback
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)


def _analyze_one(payload: Tuple) -> Dict:
    """Worker: analyze one contract, return issue dicts (run in a
    spawned process; heavyweight imports stay inside)."""
    (
        code,
        creation_code,
        name,
        address,
        strategy,
        transaction_count,
        execution_timeout,
        create_timeout,
        max_depth,
        loop_bound,
        modules,
        solver_timeout,
        use_device,
    ) = payload
    try:
        from mythril_tpu.analysis.security import fire_lasers
        from mythril_tpu.analysis.symbolic import SymExecWrapper
        from mythril_tpu.ethereum.evmcontract import EVMContract
        from mythril_tpu.support.support_args import args

        if solver_timeout:
            args.solver_timeout = solver_timeout
        if not use_device:
            # pooled workers must not contend for the one accelerator;
            # device paths run in-parent (or single-process) only
            args.device_prepass = "never"
            args.device_solving = "never"

        contract = EVMContract(
            code=code or "", creation_code=creation_code or "", name=name
        )
        sym = SymExecWrapper(
            contract,
            address,
            strategy,
            max_depth=max_depth,
            execution_timeout=execution_timeout,
            loop_bound=loop_bound,
            create_timeout=create_timeout,
            transaction_count=transaction_count,
            modules=modules,
            compulsory_statespace=False,
        )
        issues = fire_lasers(sym, modules)
        exploration = getattr(sym, "device_exploration", None)
        return {
            "name": name,
            "issues": [issue.as_dict for issue in issues],
            "states": sym.laser.total_states,
            "device_prepass": exploration["stats"] if exploration else None,
            "error": None,
        }
    except Exception:
        return {
            "name": name,
            "issues": [],
            "states": 0,
            "error": traceback.format_exc(),
        }


def analyze_corpus(
    contracts: List[Tuple[str, str, str]],
    address: int = 0x901D573B8CE8C997DE5F19173C32D966B4Fa55FE,
    strategy: str = "bfs",
    transaction_count: int = 2,
    execution_timeout: int = 60,
    create_timeout: int = 10,
    max_depth: int = 128,
    loop_bound: int = 3,
    modules: Optional[List[str]] = None,
    solver_timeout: Optional[int] = None,
    processes: Optional[int] = None,
    use_device: Optional[bool] = None,
) -> List[Dict]:
    """Analyze `contracts` = [(runtime_code_hex, creation_code_hex,
    name), ...] across a process pool; returns one result dict per
    contract ({name, issues, error})."""
    processes = processes or min(len(contracts), mp.cpu_count())
    if use_device is None:
        use_device = processes <= 1 or len(contracts) == 1
    payloads = [
        (
            code,
            creation_code,
            name,
            address,
            strategy,
            transaction_count,
            execution_timeout,
            create_timeout,
            max_depth,
            loop_bound,
            modules,
            solver_timeout,
            use_device,
        )
        for code, creation_code, name in contracts
    ]
    if processes <= 1 or len(payloads) == 1:
        return [_analyze_one(p) for p in payloads]

    ctx = mp.get_context("spawn")  # fresh singletons per worker
    with ctx.Pool(processes=processes) as pool:
        results = pool.map(_analyze_one, payloads)
    return results


def mesh_explore_corpus(
    contracts: List[Tuple[str, str, str]],
    n_devices: Optional[int] = None,
    lanes_per_contract: int = 16,
    max_steps: int = 2048,
    calldata_len: int = 68,
    seed: int = 7,
) -> Dict:
    """Corpus exploration sharded over a device mesh (SURVEY §2.4's
    per-contract-loop axis): every contract becomes a stripe of lanes
    with distinct calldata seeds, the whole wave is one lane-sharded
    StateBatch, and the mesh splits it over the dp axis — the batched
    replacement for the reference's sequential per-contract loop.

    Returns {lane_steps, wall_s, lane_steps_per_sec, contracts,
    lanes, coverage} — used by tools/corpus_bench.py --mesh.
    """
    import random
    import time as _time

    import numpy as np

    from mythril_tpu.laser.batch.run import run
    from mythril_tpu.laser.batch.seeds import code_cap_bucket, selector_seeds
    from mythril_tpu.laser.batch.state import make_batch, make_code_table
    from mythril_tpu.parallel import make_mesh, replicate_table, shard_batch

    rng = random.Random(seed)
    codes = []
    seeds_per_code = []
    for runtime_hex, _creation, _name in contracts:
        runtime_hex = runtime_hex[2:] if runtime_hex.startswith("0x") else runtime_hex
        codes.append(bytes.fromhex(runtime_hex))
        seeds_per_code.append(
            selector_seeds(runtime_hex, lanes_per_contract, calldata_len, rng)
        )

    cap = code_cap_bucket(max(len(c) for c in codes))
    table = make_code_table(codes, code_cap=cap)

    mesh = make_mesh(n_devices)
    n_dev = mesh.devices.size
    n_lanes = len(codes) * lanes_per_contract
    pad = (-n_lanes) % n_dev
    code_ids = np.array(
        [i for i in range(len(codes)) for _ in range(lanes_per_contract)]
        + [0] * pad,
        dtype=np.int32,
    )
    calldata = [d for seeds in seeds_per_code for d in seeds]
    calldata += [b"\x00" * calldata_len] * pad

    batch = make_batch(len(code_ids), code_ids=code_ids, calldata=calldata)
    batch = shard_batch(batch, mesh)
    table = replicate_table(table, mesh)

    # warm the jit cache with the SAME static args (max_steps is a
    # static jit argument — a different value compiles a different
    # executable) so the measurement is execution, not compile
    warm, _ = run(batch, table, max_steps=max_steps)
    np.asarray(warm.pc)[:1]

    t0 = _time.perf_counter()
    out, steps = run(batch, table, max_steps=max_steps)
    seen_host = np.asarray(out.pc_seen)  # the device->host sync point
    wall = _time.perf_counter() - t0
    covered = int(
        (np.unpackbits(seen_host.view(np.uint8), axis=-1) != 0).sum()
    )

    lane_steps = int(steps) * len(code_ids)
    return {
        "devices": int(n_dev),
        "contracts": len(codes),
        "lanes": len(code_ids),
        "steps": int(steps),
        "lane_steps": lane_steps,
        "wall_s": round(wall, 3),
        "lane_steps_per_sec": round(lane_steps / wall, 1),
        "covered_pc_bits": covered,
    }
