"""Report templates (jinja2 + html), shipped as package data.

This __init__ exists so setuptools' package discovery includes the
directory in wheels; the templates are loaded by analysis/report.py.
"""
