"""Witness concretization: path constraints -> exploit transactions.

Reference parity: mythril/analysis/solver.py:47-242 —
`get_transaction_sequence` poses one Optimize query (minimizing
calldata sizes and call values, with balance sanity bounds), then
extracts per-transaction concrete calldata/value/caller and the
initial account state from the model, patching keccak placeholder
values with real hashes.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Tuple, Union

from mythril_tpu.exceptions import UnsatError
from mythril_tpu.laser.ethereum.keccak_function_manager import (
    hash_matcher,
    keccak_function_manager,
)
from mythril_tpu.laser.ethereum.state.constraints import Constraints
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.ethereum.transaction import BaseTransaction
from mythril_tpu.laser.ethereum.transaction.transaction_models import (
    ContractCreationTransaction,
)
from mythril_tpu.laser.smt import UGE, symbol_factory
from mythril_tpu.laser.smt.model import Model
from mythril_tpu.support.model import get_model

log = logging.getLogger(__name__)


def pretty_print_model(model: Model) -> str:
    """Human-readable assignment dump."""
    ret = ""
    for d in model.decls():
        value = model[d]
        try:
            condition = "0x%x" % int(value)
        except (TypeError, ValueError):
            condition = str(value)
        ret += "%s: %s\n" % (d.name(), condition)
    return ret


def get_transaction_sequence(
    global_state: GlobalState, constraints: Constraints
) -> Dict:
    """Generate the concrete transaction sequence witnessing
    `constraints` (raises UnsatError when impossible)."""
    transaction_sequence = global_state.world_state.transaction_sequence

    concrete_transactions = []

    tx_constraints, minimize = _set_minimisation_constraints(
        transaction_sequence, constraints.copy(), [], 5000, global_state.world_state
    )
    model = get_model(tx_constraints, minimize=minimize)

    # initial state includes the creation account (its code technically
    # only exists after tx 1; reports follow the reference's convention)
    initial_world_state = transaction_sequence[0].world_state
    initial_accounts = initial_world_state.accounts

    for transaction in transaction_sequence:
        concrete_transactions.append(_get_concrete_transaction(model, transaction))

    min_price_dict: Dict[str, int] = {}
    for address in initial_accounts.keys():
        min_price_dict[address] = model.eval_int(
            initial_world_state.starting_balances[
                symbol_factory.BitVecVal(address, 256)
            ]
        )

    concrete_initial_state = _get_concrete_state(initial_accounts, min_price_dict)
    if isinstance(transaction_sequence[0], ContractCreationTransaction):
        code = transaction_sequence[0].code
        _replace_with_actual_sha(concrete_transactions, model, code)
    else:
        _replace_with_actual_sha(concrete_transactions, model)
    _add_calldata_placeholder(concrete_transactions, transaction_sequence)

    return {"initialState": concrete_initial_state, "steps": concrete_transactions}


def _add_calldata_placeholder(
    concrete_transactions: List[Dict[str, str]],
    transaction_sequence: List[BaseTransaction],
) -> None:
    """Mirror `input` into `calldata` (for a creation tx, without the
    deployment bytecode prefix)."""
    for tx in concrete_transactions:
        tx["calldata"] = tx["input"]
    if not isinstance(transaction_sequence[0], ContractCreationTransaction):
        return
    code_len = len(transaction_sequence[0].code.bytecode)
    concrete_transactions[0]["calldata"] = concrete_transactions[0]["input"][
        code_len + 2 :
    ]


def _replace_with_actual_sha(
    concrete_transactions: List[Dict[str, str]], model: Model, code=None
) -> None:
    """Substitute placeholder hash values (in the reserved fffffff...
    intervals) with real keccaks of the witness preimages."""
    concrete_hashes = keccak_function_manager.get_concrete_hash_data(model)
    for tx in concrete_transactions:
        if hash_matcher not in tx["input"]:
            continue
        if code is not None and code.bytecode in tx["input"]:
            s_index = len(code.bytecode) + 2
        else:
            s_index = 10
        for i in range(s_index, len(tx["input"])):
            data_slice = tx["input"][i : i + 64]
            if hash_matcher not in data_slice or len(data_slice) != 64:
                continue
            find_input = symbol_factory.BitVecVal(int(data_slice, 16), 256)
            input_ = None
            for size in concrete_hashes:
                _, inverse = keccak_function_manager.store_function[size]
                if find_input.value not in concrete_hashes[size]:
                    continue
                input_ = symbol_factory.BitVecVal(
                    model.eval_int(inverse(find_input)), size
                )
            if input_ is None:
                continue
            keccak = keccak_function_manager.find_concrete_keccak(input_)
            hex_keccak = "{:064x}".format(keccak.value)
            tx["input"] = tx["input"][:s_index] + tx["input"][s_index:].replace(
                tx["input"][i : 64 + i], hex_keccak
            )


def _get_concrete_state(
    initial_accounts: Dict, min_price_dict: Dict[str, int]
) -> Dict:
    accounts = {}
    for address, account in initial_accounts.items():
        data: Dict[str, Union[int, str]] = {
            "nonce": account.nonce,
            "code": account.code.bytecode,
            "storage": str(account.storage),
            "balance": hex(min_price_dict.get(address, 0)),
        }
        accounts[hex(address)] = data
    return {"accounts": accounts}


def _get_concrete_transaction(model: Model, transaction: BaseTransaction) -> Dict:
    address = hex(transaction.callee_account.address.value)
    value = model.eval_int(transaction.call_value)
    caller = "0x" + ("%x" % model.eval_int(transaction.caller)).zfill(40)

    input_ = ""
    if isinstance(transaction, ContractCreationTransaction):
        address = ""
        input_ += transaction.code.bytecode

    input_ += "".join(
        "{:02x}".format(b if isinstance(b, int) else (b.value or 0))
        for b in transaction.call_data.concrete(model)
    )

    return {
        "input": "0x" + input_,
        "value": "0x%x" % value,
        "origin": caller,
        "address": "%s" % address,
    }


def _set_minimisation_constraints(
    transaction_sequence, constraints, minimize, max_size, world_state
) -> Tuple[Constraints, tuple]:
    """Bound calldata sizes and starting balances; minimize calldata
    size + call value per transaction (reference: solver.py:205)."""
    for transaction in transaction_sequence:
        max_calldata_size = symbol_factory.BitVecVal(max_size, 256)
        constraints.append(UGE(max_calldata_size, transaction.call_data.calldatasize))

        minimize.append(transaction.call_data.calldatasize)
        minimize.append(transaction.call_value)
        constraints.append(
            UGE(
                symbol_factory.BitVecVal(1000000000000000000000, 256),
                world_state.starting_balances[transaction.caller],
            )
        )

    for account in world_state.accounts.values():
        # each account starts with < 100 ETH: keeps witnesses readable
        # and avoids balance-overflow artifacts
        constraints.append(
            UGE(
                symbol_factory.BitVecVal(100000000000000000000, 256),
                world_state.starting_balances[account.address],
            )
        )

    return constraints, tuple(minimize)
