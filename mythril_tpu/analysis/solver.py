"""Witness concretization: path constraints -> exploit transactions.

API parity with the reference's mythril/analysis/solver.py:47-242 —
`get_transaction_sequence(global_state, constraints)` is the entry
every detection module calls, and the returned dict shape
(`{"initialState": ..., "steps": [...]}`) is the report contract.

The mechanics are organized differently from the reference: one
`WitnessBuilder` pass owns the whole concretization — it poses a
single bounded minimization query, renders each transaction step from
the model, and patches keccak placeholders through a precomputed
substitution table instead of rescanning the calldata hex position by
position.
"""

from __future__ import annotations

import logging
from typing import Dict, List

from mythril_tpu.laser.ethereum.keccak_function_manager import (
    hash_matcher,
    keccak_function_manager,
)
from mythril_tpu.laser.ethereum.state.constraints import Constraints
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.ethereum.transaction.transaction_models import (
    ContractCreationTransaction,
)
from mythril_tpu.laser.smt import UGE, symbol_factory
from mythril_tpu.laser.smt.model import Model
from mythril_tpu.support.model import get_model
from mythril_tpu.support.phase_profile import PhaseProfile

log = logging.getLogger(__name__)


def pretty_print_model(model: Model) -> str:
    """Human-readable assignment dump."""
    rows = []
    for decl in model.decls():
        value = model[decl]
        try:
            rendered = "0x%x" % int(value)
        except (TypeError, ValueError):
            rendered = str(value)
        rows.append(f"{decl.name()}: {rendered}")
    return "".join(row + "\n" for row in rows)


def get_transaction_sequence(
    global_state: GlobalState, constraints: Constraints
) -> Dict:
    """Generate the concrete transaction sequence witnessing
    `constraints` (raises UnsatError when impossible)."""
    return WitnessBuilder(global_state, constraints).build()


def _word(value: int):
    return symbol_factory.BitVecVal(value, 256)


class WitnessBuilder:
    """One concretization pass: solve once, render every step."""

    #: calldata bytes per transaction the witness may use
    CALLDATA_CAP = 5000
    #: spendable funds cap per transaction sender
    SENDER_FUNDS_CAP = 10**21
    #: starting-balance cap per account: keeps witnesses readable and
    #: avoids balance-overflow artifacts (reference: solver.py:205)
    ACCOUNT_FUNDS_CAP = 10**20

    def __init__(self, global_state: GlobalState, constraints: Constraints):
        self.world = global_state.world_state
        self.transactions = self.world.transaction_sequence
        self.query = constraints.copy()
        # the first transaction's world state is rendered as the
        # initial state; by reference convention it already carries
        # the created account (code technically exists only after tx 1)
        self.genesis = self.transactions[0].world_state

    # -- the solve -----------------------------------------------------
    def _solve(self) -> Model:
        """One bounded query minimizing calldata sizes and call
        values, lexicographically per transaction."""
        goals = []
        for tx in self.transactions:
            size = tx.call_data.calldatasize
            self.query.append(UGE(_word(self.CALLDATA_CAP), size))
            self.query.append(
                UGE(
                    _word(self.SENDER_FUNDS_CAP),
                    self.world.starting_balances[tx.caller],
                )
            )
            goals.append(size)
            goals.append(tx.call_value)
        for account in self.world.accounts.values():
            self.query.append(
                UGE(
                    _word(self.ACCOUNT_FUNDS_CAP),
                    self.world.starting_balances[account.address],
                )
            )
        with PhaseProfile().measure("concretize"):
            return get_model(self.query, minimize=tuple(goals))

    # -- rendering -----------------------------------------------------
    @property
    def _creation_code_hex(self) -> str:
        first = self.transactions[0]
        if isinstance(first, ContractCreationTransaction):
            return first.code.bytecode
        return ""

    def _render_step(self, model: Model, tx) -> Dict[str, str]:
        deploying = isinstance(tx, ContractCreationTransaction)
        body = tx.code.bytecode if deploying else ""
        body += "".join(
            "{:02x}".format(b if isinstance(b, int) else (b.value or 0))
            for b in tx.call_data.concrete(model)
        )
        return {
            "input": "0x" + body,
            "value": "0x%x" % model.eval_int(tx.call_value),
            "origin": "0x" + ("%x" % model.eval_int(tx.caller)).zfill(40),
            "address": (
                "" if deploying else hex(tx.callee_account.address.value)
            ),
        }

    def _initial_state(self, model: Model) -> Dict:
        accounts = {}
        for address, account in self.genesis.accounts.items():
            balance = model.eval_int(
                self.genesis.starting_balances[_word(address)]
            )
            accounts[hex(address)] = {
                "nonce": account.nonce,
                "code": account.code.bytecode,
                "storage": str(account.storage),
                "balance": hex(balance),
            }
        return {"accounts": accounts}

    # -- keccak placeholder patching -----------------------------------
    def _hash_substitutions(self, model: Model) -> Dict[str, str]:
        """placeholder-hex -> real-keccak-hex for every placeholder
        the model bound to a concrete preimage (the reserved
        fffffff... intervals the keccak manager hands out)."""
        table: Dict[str, str] = {}
        by_size = keccak_function_manager.get_concrete_hash_data(model)
        for size, placeholders in by_size.items():
            _, inverse = keccak_function_manager.store_function[size]
            for placeholder in placeholders:
                if placeholder is None:
                    continue
                preimage = symbol_factory.BitVecVal(
                    model.eval_int(inverse(_word(placeholder))), size
                )
                real = keccak_function_manager.find_concrete_keccak(preimage)
                table["{:064x}".format(placeholder)] = "{:064x}".format(
                    real.value
                )
        return table

    def _patch_hashes(self, steps: List[Dict[str, str]], model: Model) -> None:
        if not any(hash_matcher in step["input"] for step in steps):
            return
        table = self._hash_substitutions(model)
        if not table:
            return
        code_hex = self._creation_code_hex
        for step in steps:
            data = step["input"]
            # never rewrite bytes inside the deployment code prefix
            keep = (
                len(code_hex) + 2
                if code_hex and code_hex in data
                else len("0x") + 8
            )
            tail = data[keep:]
            for placeholder, real in table.items():
                if hash_matcher in placeholder and placeholder in tail:
                    tail = tail.replace(placeholder, real)
            step["input"] = data[:keep] + tail

    # -- assembly ------------------------------------------------------
    @staticmethod
    def _mirror_calldata(steps: List[Dict[str, str]], code_hex: str) -> None:
        """`calldata` mirrors `input`; a creation step's calldata is
        the constructor arguments only (deployment bytecode stripped)."""
        for step in steps:
            step["calldata"] = step["input"]
        if code_hex:
            steps[0]["calldata"] = steps[0]["input"][len(code_hex) + 2 :]

    def build(self) -> Dict:
        model = self._solve()
        steps = [self._render_step(model, tx) for tx in self.transactions]
        self._patch_hashes(steps, model)
        self._mirror_calldata(steps, self._creation_code_hex)
        return {"initialState": self._initial_state(model), "steps": steps}
