"""Helpers describing EVM operations for POST-style analysis.

Reference parity: mythril/analysis/ops.py:9-93 — `VarType`,
`Variable`, `get_variable` (concrete-or-symbolic classifier) and the
`Call` record SymExecWrapper extracts from the statespace.
"""

from __future__ import annotations

from enum import Enum

from mythril_tpu.laser.ethereum import util
from mythril_tpu.laser.smt import simplify


class VarType(Enum):
    SYMBOLIC = 1
    CONCRETE = 2


class Variable:
    """A value tagged with its concreteness."""

    def __init__(self, val, _type):
        self.val = val
        self.type = _type

    def __str__(self):
        return str(self.val)


def get_variable(i) -> Variable:
    try:
        return Variable(util.get_concrete_int(i), VarType.CONCRETE)
    except TypeError:
        return Variable(simplify(i), VarType.SYMBOLIC)


class Op:
    """Base for operations referencing node/state in the statespace."""

    def __init__(self, node, state, state_index):
        self.node = node
        self.state = state
        self.state_index = state_index


class Call(Op):
    """A recorded CALL-family operation."""

    def __init__(
        self,
        node,
        state,
        state_index,
        _type,
        to,
        gas,
        value=Variable(0, VarType.CONCRETE),
        data=None,
    ):
        super().__init__(node, state, state_index)
        self.to = to
        self.gas = gas
        self.type = _type
        self.value = value
        self.data = data
