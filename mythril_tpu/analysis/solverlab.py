"""`myth solverlab`: the offline solver replay lab.

A corpus captured with ``--capture-queries DIR`` (observe/querylog.py)
holds every solved SMT query as a content-addressed, replayable
artifact. This module re-runs such a corpus against any engine matrix
— host CDCL, the on-chip portfolio per shape bucket, or the full
production race funnel — and reports per-engine verdict/wall/agreement
tables plus the funnel-loss waterfall. Portfolio tuning (ROADMAP item
1: "make the on-device solver actually win") iterates here in seconds
on a fixed query set instead of re-running full corpus analyses in
minutes.

Engines:

- ``host``    native CDCL alone (device gate closed), conflict-budgeted
              so the replay verdict is a pure function of the query —
              this leg must reproduce the live verdicts
- ``device``  the device funnel alone: compile to the shape bucket,
              exhaustively enumerate small complete spaces (a genuine
              unsat verdict), else the diversified stochastic local
              search plus the cube-and-conquer fan; any witness is
              validated by concrete evaluation ("unknown" proves
              nothing and counts as *incomplete*, not disagreement)
- ``race``    the production funnel with the device gate forced open
              (sprint -> race -> marathon), answering "would the race
              win this query today?"

``--shard I/N`` replays only the content-hash shard ``I`` — the same
deterministic partition the corpus driver uses, so a mesh of N hosts
replays a large corpus in parallel with no coordination.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

from mythril_tpu.observe import querylog

log = logging.getLogger(__name__)

REPORT_SCHEMA_VERSION = 1

ENGINES = ("host", "device", "race")

#: replay verdicts beyond the solver's sat/unsat/unknown
UNSUPPORTED = "unsupported"  # outside the device language / limb cap
INVALID = "invalid"  # witness failed the concrete soundness gate
ERROR = "error"  # engine raised; the artifact names the query

_DECIDED = ("sat", "unsat")


def parse_shard(spec: Optional[str]) -> Optional[Tuple[int, int]]:
    """``I/N`` -> (I, N); validates bounds."""
    if not spec:
        return None
    try:
        index_s, count_s = spec.split("/", 1)
        index, count = int(index_s), int(count_s)
    except ValueError:
        raise ValueError(f"--shard wants I/N, got {spec!r}")
    if count < 1 or not 0 <= index < count:
        raise ValueError(f"--shard index out of range: {spec!r}")
    return index, count


def shard_corpus(
    corpus: List[Dict], shard: Optional[Tuple[int, int]]
) -> List[Dict]:
    """The deterministic content-hash partition (mesh replay)."""
    if shard is None:
        return corpus
    index, count = shard
    return [
        a for a in corpus if int(a["sha"][:16], 16) % count == index
    ]


# ---------------------------------------------------------------------------
# engines
# ---------------------------------------------------------------------------


def _rebuild(artifact: Dict) -> List:
    return querylog.deserialize_terms(artifact["program"])


def _replay_host(lowered: List, timeout_ms: int) -> str:
    """The CDCL alone, conflict-budgeted: deterministic given the
    query whenever the wall valve doesn't fire (same contract as
    --deterministic-solving)."""
    from mythril_tpu.laser.smt.solver.solver import check_terms
    from mythril_tpu.support.support_args import args as _args

    restore = (_args.device_solving, _args.parallel_solving)
    _args.device_solving = "never"
    _args.parallel_solving = False
    try:
        verdict, _model = check_terms(
            lowered, timeout_ms=timeout_ms, conflict_budget=timeout_ms * 8
        )
    finally:
        _args.device_solving, _args.parallel_solving = restore
    return verdict


def _replay_device(
    lowered: List, candidates: int, steps: int
) -> Tuple[str, Optional[str]]:
    """The device funnel alone (enumeration + diversified SLS +
    cube-and-conquer, no host rungs); returns (verdict, loss_reason).
    A found witness is believed only after concretely satisfying every
    root — the same validation gate production models pass — and a
    complete enumeration's unsat is a genuine device-owned verdict."""
    from mythril_tpu.laser.smt.evalterm import eval_term
    from mythril_tpu.laser.smt.solver import portfolio

    prog, compile_loss = portfolio.compile_program_ex(lowered)
    if prog is None:
        return UNSUPPORTED, compile_loss
    if not prog.var_slots:
        return UNSUPPORTED, querylog.LOSS_QUERY_TRIVIAL
    verdict = portfolio.device_solve_batch(
        [lowered], candidates=candidates, steps=steps
    )[0]
    if verdict.status == "unsat":
        return "unsat", None
    if verdict.status != "sat":
        return "unknown", verdict.loss
    assignment = verdict.assignment
    try:
        if all(eval_term(c, assignment) for c in lowered):
            return "sat", None
    except Exception:
        log.debug("witness evaluation failed", exc_info=True)
    return INVALID, querylog.LOSS_WITNESS_INVALID


def _replay_race(lowered: List, timeout_ms: int) -> str:
    """The production funnel with the device gate forced open."""
    from mythril_tpu.laser.smt.solver.solver import check_terms
    from mythril_tpu.support.support_args import args as _args

    restore = (_args.device_solving, _args.parallel_solving)
    _args.device_solving = "always"
    _args.parallel_solving = True
    try:
        verdict, _model = check_terms(lowered, timeout_ms=timeout_ms)
    finally:
        _args.device_solving, _args.parallel_solving = restore
    return verdict


def _classify(live: str, replayed: str) -> str:
    if replayed == live:
        return "agree"
    if replayed in _DECIDED and live in _DECIDED:
        return "disagree"
    return "incomplete"


# ---------------------------------------------------------------------------
# the lab
# ---------------------------------------------------------------------------


def waterfall(corpus: Sequence[Dict]) -> Dict:
    """The funnel-loss report of a corpus as CAPTURED: loss reasons
    overall and restricted to host-WON (sat) queries, origins,
    shape-bucket population, per-engine live verdicts."""
    losses: Dict[str, int] = {}
    losses_sat: Dict[str, int] = {}
    origins: Dict[str, int] = {}
    buckets: Dict[str, int] = {}
    verdicts: Dict[str, int] = {}
    for artifact in corpus:
        verdict = artifact.get("verdict", "unknown")
        verdicts[verdict] = verdicts.get(verdict, 0) + 1
        origin = artifact.get("origin", "?")
        origins[origin] = origins.get(origin, 0) + 1
        reason = artifact.get("loss_reason")
        if reason:
            losses[reason] = losses.get(reason, 0) + 1
            if verdict == "sat":
                losses_sat[reason] = losses_sat.get(reason, 0) + 1
        bucket = artifact.get("bucket")
        key = (
            "n{nodes}/c{consts}/r{roots}/v{vars}/L{limbs}".format(**bucket)
            if bucket
            else artifact.get("compile_loss") or "uncompiled"
        )
        buckets[key] = buckets.get(key, 0) + 1
    return {
        "queries": len(corpus),
        "live_verdicts": verdicts,
        "origins": origins,
        "buckets": buckets,
        "loss_waterfall": losses,
        "loss_waterfall_sat": losses_sat,
    }


def replay_corpus(
    corpus: Sequence[Dict],
    engines: Sequence[str] = ("host", "device"),
    timeout_ms: int = 10_000,
    candidates: int = 64,
    steps: int = 512,
) -> Dict:
    """Re-run every artifact against `engines`; returns the report
    dict (waterfall + per-engine verdict/wall/agreement tables +
    disagreement details). Capture is disarmed for the duration so the
    replay never mutates the corpus it reads."""
    for engine in engines:
        if engine not in ENGINES:
            raise ValueError(f"unknown engine {engine!r} (have {ENGINES})")
    report = waterfall(corpus)
    report["schema_version"] = REPORT_SCHEMA_VERSION
    report["engines"] = list(engines)
    tables: Dict[str, Dict] = {
        engine: {
            "verdicts": {},
            "wall_s": 0.0,
            "agreement": {"agree": 0, "disagree": 0, "incomplete": 0},
        }
        for engine in engines
    }
    disagreements: List[Dict] = []

    prev_capture = querylog.capture_dir()
    querylog.configure_capture(None)
    try:
        for artifact in corpus:
            live = artifact.get("verdict", "unknown")
            try:
                lowered = _rebuild(artifact)
            except Exception as why:
                log.warning(
                    "artifact %s did not rebuild: %s", artifact["sha"], why
                )
                for engine in engines:
                    table = tables[engine]
                    table["verdicts"][ERROR] = (
                        table["verdicts"].get(ERROR, 0) + 1
                    )
                    table["agreement"]["incomplete"] += 1
                continue
            row = {"sha": artifact["sha"], "live": live}
            for engine in engines:
                t0 = time.perf_counter()
                try:
                    if engine == "host":
                        verdict = _replay_host(lowered, timeout_ms)
                    elif engine == "device":
                        verdict, _loss = _replay_device(
                            lowered, candidates, steps
                        )
                    else:
                        verdict = _replay_race(lowered, timeout_ms)
                except Exception as why:
                    log.debug(
                        "engine %s failed on %s: %s",
                        engine, artifact["sha"], why, exc_info=True,
                    )
                    verdict = ERROR
                wall = time.perf_counter() - t0
                table = tables[engine]
                table["verdicts"][verdict] = (
                    table["verdicts"].get(verdict, 0) + 1
                )
                table["wall_s"] += wall
                outcome = _classify(live, verdict)
                table["agreement"][outcome] += 1
                row[engine] = verdict
                if outcome == "disagree":
                    row["disagree"] = True
            if row.get("disagree") and len(disagreements) < 32:
                disagreements.append(row)
    finally:
        querylog.configure_capture(prev_capture)

    for engine, table in tables.items():
        table["wall_s"] = round(table["wall_s"], 3)
        n = len(corpus)
        table["agreement_pct"] = (
            round(100.0 * table["agreement"]["agree"] / n, 1) if n else 100.0
        )
    report["replay"] = tables
    report["disagreements"] = disagreements
    return report


# ---------------------------------------------------------------------------
# portfolio tuning: `myth solverlab tune`
# ---------------------------------------------------------------------------

#: the tunable diversified-portfolio knobs and their sweep axes. The
#: committed winners live in portfolio.PORTFOLIO_DEFAULTS — re-run the
#: tune against a fresh capture before changing them by hand.
TUNE_GRID: Dict[str, List] = {
    "noise_lo": [0.0, 0.02, 0.08],
    "noise_hi": [0.2, 0.4, 0.6],
    "greedy_frac": [0.25, 0.5, 0.75],
    "restart_base": [12, 24, 48],
    "seeded_frac": [0.0, 0.25, 0.5],
    "cube_depth": [0, 2, 3, 4],
    "first_pass_steps": [96, 192, 384],
}


def _tune_trial(
    lowered_queries: List[List], candidates: int, knobs: Dict
) -> Dict:
    """One sweep point: replay the device funnel over the corpus under
    `portfolio_overrides(**knobs)`. Scored on decided queries (sat +
    device-owned unsat, witnesses validated inside device_solve_batch)
    then wall — more verdicts first, faster second."""
    from mythril_tpu.laser.smt.solver import portfolio

    t0 = time.perf_counter()
    with portfolio.portfolio_overrides(**knobs):
        verdicts = portfolio.device_solve_batch(
            lowered_queries, candidates=candidates
        )
    wall = time.perf_counter() - t0
    sat_n = sum(1 for v in verdicts if v.status == "sat")
    unsat_n = sum(1 for v in verdicts if v.status == "unsat")
    return {
        "knobs": dict(knobs),
        "sat": sat_n,
        "unsat": unsat_n,
        "decided": sat_n + unsat_n,
        "unknown": len(verdicts) - sat_n - unsat_n,
        "wall_s": round(wall, 3),
    }


def tune_corpus(
    corpus: Sequence[Dict],
    trials: int = 12,
    sweep: str = "random",
    seed: int = 1,
    candidates: int = 64,
) -> Dict:
    """Sweep the portfolio knobs over a captured corpus and rank the
    results — the offline lab that derives PORTFOLIO_DEFAULTS.

    ``sweep="grid"`` walks one knob at a time off the current defaults
    (a coordinate sweep: len(axis) trials per knob, `trials` ignored);
    ``sweep="random"`` samples `trials` random grid combinations.
    Every trial recompiles the search kernels (the knobs are
    trace-time constants), so this is replay-lab cost by design —
    run it offline, commit the winner."""
    import random as _random

    from mythril_tpu.laser.smt.solver import portfolio

    lowered_queries: List[List] = []
    for artifact in corpus:
        try:
            lowered_queries.append(_rebuild(artifact))
        except Exception as why:
            log.warning(
                "artifact %s did not rebuild: %s", artifact["sha"], why
            )
    report: Dict = {
        "schema_version": REPORT_SCHEMA_VERSION,
        "queries": len(lowered_queries),
        "sweep": sweep,
        "defaults": dict(portfolio.PORTFOLIO_DEFAULTS),
    }
    results: List[Dict] = []
    if not lowered_queries:
        report["trials"] = results
        return report
    # the committed defaults are always trial 0 — the bar to beat
    baseline = _tune_trial(lowered_queries, candidates, {})
    baseline["baseline"] = True
    results.append(baseline)
    if sweep == "grid":
        for knob, axis in sorted(TUNE_GRID.items()):
            for value in axis:
                if value == portfolio.PORTFOLIO_DEFAULTS.get(knob):
                    continue  # the baseline already covers it
                results.append(
                    _tune_trial(
                        lowered_queries, candidates, {knob: value}
                    )
                )
    elif sweep == "random":
        rng = _random.Random(seed)
        seen = set()
        for _ in range(max(0, trials)):
            knobs = {
                knob: rng.choice(axis)
                for knob, axis in sorted(TUNE_GRID.items())
            }
            key = tuple(sorted(knobs.items()))
            if key in seen:
                continue
            seen.add(key)
            results.append(_tune_trial(lowered_queries, candidates, knobs))
    else:
        raise ValueError(f"unknown sweep {sweep!r} (grid|random)")
    ranked = sorted(
        results, key=lambda r: (-r["decided"], r["wall_s"])
    )
    report["trials"] = ranked
    report["best"] = ranked[0]
    report["beats_baseline"] = (
        ranked[0] is not baseline
        and ranked[0]["decided"] > baseline["decided"]
    )
    return report


def run(
    corpus_dir: str,
    mode: str = "replay",
    engines: Sequence[str] = ("host", "device"),
    timeout_ms: int = 10_000,
    candidates: int = 64,
    steps: int = 512,
    reason: Optional[str] = None,
    origin: Optional[str] = None,
    shard: Optional[str] = None,
    trials: int = 12,
    sweep: str = "random",
    tune_seed: int = 1,
    watch: bool = False,
    watch_out: Optional[str] = None,
    watch_interval_s: float = 30.0,
    watch_min_new: int = 8,
    watch_rounds: int = 0,
) -> Dict:
    """Load + filter + shard a corpus, then replay, tune, or report.

    ``mode="tune", watch=True`` is the continuous-self-tuning loop
    (`myth solverlab tune --watch`): instead of one sweep it delegates
    to routing/tuning.py's watcher, which re-tunes as the capture
    corpus grows and promotes gate-passing winners as versioned
    ``tuned-v<N>.json`` override artifacts in `watch_out`."""
    if watch:
        if mode != "tune":
            raise ValueError("--watch only applies to `solverlab tune`")
        from mythril_tpu.routing import tuning as _tuning

        return _tuning.tune_watch(
            corpus_dir,
            watch_out or corpus_dir,
            interval_s=watch_interval_s,
            min_new=watch_min_new,
            rounds=watch_rounds,
            trials=trials,
            sweep=sweep,
            tune_seed=tune_seed,
            candidates=candidates,
            timeout_ms=timeout_ms,
            reason=reason,
            origin=origin,
        )
    corpus = querylog.load_corpus(corpus_dir, reason=reason, origin=origin)
    corpus = shard_corpus(corpus, parse_shard(shard))
    if mode == "report":
        report = waterfall(corpus)
        report["schema_version"] = REPORT_SCHEMA_VERSION
    elif mode == "tune":
        report = tune_corpus(
            corpus,
            trials=trials,
            sweep=sweep,
            seed=tune_seed,
            candidates=candidates,
        )
    else:
        report = replay_corpus(
            corpus,
            engines=engines,
            timeout_ms=timeout_ms,
            candidates=candidates,
            steps=steps,
        )
    report["corpus_dir"] = corpus_dir
    report["mode"] = mode
    if reason or origin:
        report["filter"] = {"reason": reason, "origin": origin}
    if shard:
        report["shard"] = shard
    return report


def render_tune_text(report: Dict) -> str:
    """The human view of a tune sweep: the ranked knob table."""
    lines = [
        "solverlab tune: {q} quer{y} from {d} ({s} sweep)".format(
            q=report.get("queries", 0),
            y="y" if report.get("queries") == 1 else "ies",
            d=report.get("corpus_dir", "?"),
            s=report.get("sweep", "?"),
        )
    ]
    trials = report.get("trials") or []
    if not trials:
        lines.append("  (no replayable queries in the corpus)")
        return "\n".join(lines)
    lines.append(
        f"  {'rank':<5}{'decided':<9}{'sat':<6}{'unsat':<7}"
        f"{'wall_s':<9}knobs"
    )
    for rank, row in enumerate(trials, 1):
        tag = " (baseline: committed defaults)" if row.get("baseline") else ""
        knobs = (
            " ".join(
                f"{k}={v}" for k, v in sorted(row["knobs"].items())
            )
            or "-"
        )
        lines.append(
            f"  {rank:<5}{row['decided']:<9}{row['sat']:<6}"
            f"{row['unsat']:<7}{row['wall_s']:<9}{knobs}{tag}"
        )
    if report.get("beats_baseline"):
        lines.append(
            "  -> the winner BEATS the committed defaults: consider "
            "updating portfolio.PORTFOLIO_DEFAULTS"
        )
    else:
        lines.append("  -> the committed defaults hold")
    return "\n".join(lines)


def render_watch_text(report: Dict) -> str:
    """The human view of a tune-watch run: one row per round."""
    lines = [
        "solverlab tune --watch: {d} -> {o}".format(
            d=report.get("corpus_dir", "?"), o=report.get("out_dir", "?")
        )
    ]
    for row in report.get("rounds") or []:
        bits = [
            f"round {row['round']}: {row['queries']} queries "
            f"({row['new']} new)"
        ]
        if "beats_baseline" in row:
            bits.append(
                "winner beats baseline"
                if row["beats_baseline"]
                else "defaults hold"
            )
        gate = row.get("gate")
        if gate:
            bits.append(
                "gate {}: agree {} / disagree {} / incomplete {}".format(
                    "PASS" if gate["pass"] else "FAIL",
                    gate["agree"], gate["disagree"], gate["incomplete"],
                )
            )
        if row.get("promoted"):
            bits.append(f"promoted -> {row['promoted']}")
        lines.append("  " + "; ".join(bits))
    lines.append(
        f"  promoted artifact: {report.get('promoted') or '(none)'}"
    )
    return "\n".join(lines)


def render_text(report: Dict) -> str:
    """The human view: waterfall + agreement tables."""
    if report.get("mode") == "tune-watch":
        return render_watch_text(report)
    if report.get("mode") == "tune" or "trials" in report:
        return render_tune_text(report)
    lines = [
        "solverlab: {queries} quer{y} from {dir}".format(
            queries=report["queries"],
            y="y" if report["queries"] == 1 else "ies",
            dir=report.get("corpus_dir", "?"),
        )
    ]
    if report.get("filter"):
        lines.append(f"  filter: {report['filter']}")
    if report.get("shard"):
        lines.append(f"  shard: {report['shard']}")
    lines.append("  live verdicts: " + _fmt_counts(report["live_verdicts"]))
    lines.append("  origins:       " + _fmt_counts(report["origins"]))
    lines.append("  loss waterfall (device-lost verdicts):")
    losses = report["loss_waterfall"]
    sat_losses = report.get("loss_waterfall_sat", {})
    for reason in sorted(losses, key=losses.get, reverse=True):
        lines.append(
            f"    {reason:<22} {losses[reason]:>6}"
            f"   (host-won: {sat_losses.get(reason, 0)})"
        )
    if not losses:
        lines.append("    (none recorded)")
    lines.append("  shape buckets: " + _fmt_counts(report["buckets"]))
    for engine, table in (report.get("replay") or {}).items():
        agreement = table["agreement"]
        lines.append(
            f"  engine {engine:<7} verdicts "
            f"{_fmt_counts(table['verdicts'])}  wall {table['wall_s']}s"
        )
        lines.append(
            f"         {'':<7} agreement {table['agreement_pct']}% "
            f"(agree {agreement['agree']} / disagree "
            f"{agreement['disagree']} / incomplete "
            f"{agreement['incomplete']})"
        )
    for row in report.get("disagreements") or []:
        lines.append(f"  DISAGREE {row}")
    return "\n".join(lines)


def _fmt_counts(table: Dict[str, int]) -> str:
    if not table:
        return "(none)"
    return " ".join(
        f"{key}={table[key]}"
        for key in sorted(table, key=table.get, reverse=True)
    )
