"""`myth solverlab`: the offline solver replay lab.

A corpus captured with ``--capture-queries DIR`` (observe/querylog.py)
holds every solved SMT query as a content-addressed, replayable
artifact. This module re-runs such a corpus against any engine matrix
— host CDCL, the on-chip portfolio per shape bucket, or the full
production race funnel — and reports per-engine verdict/wall/agreement
tables plus the funnel-loss waterfall. Portfolio tuning (ROADMAP item
1: "make the on-device solver actually win") iterates here in seconds
on a fixed query set instead of re-running full corpus analyses in
minutes.

Engines:

- ``host``    native CDCL alone (device gate closed), conflict-budgeted
              so the replay verdict is a pure function of the query —
              this leg must reproduce the live verdicts
- ``device``  the portfolio alone: compile to the shape bucket, run the
              stochastic local search, validate any witness by concrete
              evaluation (an incomplete engine: "unknown" proves
              nothing and counts as *incomplete*, not disagreement)
- ``race``    the production funnel with the device gate forced open
              (sprint -> race -> marathon), answering "would the race
              win this query today?"

``--shard I/N`` replays only the content-hash shard ``I`` — the same
deterministic partition the corpus driver uses, so a mesh of N hosts
replays a large corpus in parallel with no coordination.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

from mythril_tpu.observe import querylog

log = logging.getLogger(__name__)

REPORT_SCHEMA_VERSION = 1

ENGINES = ("host", "device", "race")

#: replay verdicts beyond the solver's sat/unsat/unknown
UNSUPPORTED = "unsupported"  # outside the device language / limb cap
INVALID = "invalid"  # witness failed the concrete soundness gate
ERROR = "error"  # engine raised; the artifact names the query

_DECIDED = ("sat", "unsat")


def parse_shard(spec: Optional[str]) -> Optional[Tuple[int, int]]:
    """``I/N`` -> (I, N); validates bounds."""
    if not spec:
        return None
    try:
        index_s, count_s = spec.split("/", 1)
        index, count = int(index_s), int(count_s)
    except ValueError:
        raise ValueError(f"--shard wants I/N, got {spec!r}")
    if count < 1 or not 0 <= index < count:
        raise ValueError(f"--shard index out of range: {spec!r}")
    return index, count


def shard_corpus(
    corpus: List[Dict], shard: Optional[Tuple[int, int]]
) -> List[Dict]:
    """The deterministic content-hash partition (mesh replay)."""
    if shard is None:
        return corpus
    index, count = shard
    return [
        a for a in corpus if int(a["sha"][:16], 16) % count == index
    ]


# ---------------------------------------------------------------------------
# engines
# ---------------------------------------------------------------------------


def _rebuild(artifact: Dict) -> List:
    return querylog.deserialize_terms(artifact["program"])


def _replay_host(lowered: List, timeout_ms: int) -> str:
    """The CDCL alone, conflict-budgeted: deterministic given the
    query whenever the wall valve doesn't fire (same contract as
    --deterministic-solving)."""
    from mythril_tpu.laser.smt.solver.solver import check_terms
    from mythril_tpu.support.support_args import args as _args

    restore = (_args.device_solving, _args.parallel_solving)
    _args.device_solving = "never"
    _args.parallel_solving = False
    try:
        verdict, _model = check_terms(
            lowered, timeout_ms=timeout_ms, conflict_budget=timeout_ms * 8
        )
    finally:
        _args.device_solving, _args.parallel_solving = restore
    return verdict


def _replay_device(
    lowered: List, candidates: int, steps: int
) -> Tuple[str, Optional[str]]:
    """The portfolio alone; returns (verdict, loss_reason). A found
    witness is believed only after concretely satisfying every root —
    the same soundness gate production models pass."""
    from mythril_tpu.laser.smt.evalterm import eval_term
    from mythril_tpu.laser.smt.solver import portfolio

    prog, compile_loss = portfolio.compile_program_ex(lowered)
    if prog is None:
        return UNSUPPORTED, compile_loss
    if not prog.var_slots:
        return UNSUPPORTED, querylog.LOSS_QUERY_TRIVIAL
    assignment = portfolio.device_check(
        lowered, candidates=candidates, steps=steps, prog=prog
    )
    if assignment is None:
        return "unknown", querylog.LOSS_SLS_NONCONVERGED
    try:
        if all(eval_term(c, assignment) for c in lowered):
            return "sat", None
    except Exception:
        log.debug("witness evaluation failed", exc_info=True)
    return INVALID, querylog.LOSS_WITNESS_INVALID


def _replay_race(lowered: List, timeout_ms: int) -> str:
    """The production funnel with the device gate forced open."""
    from mythril_tpu.laser.smt.solver.solver import check_terms
    from mythril_tpu.support.support_args import args as _args

    restore = (_args.device_solving, _args.parallel_solving)
    _args.device_solving = "always"
    _args.parallel_solving = True
    try:
        verdict, _model = check_terms(lowered, timeout_ms=timeout_ms)
    finally:
        _args.device_solving, _args.parallel_solving = restore
    return verdict


def _classify(live: str, replayed: str) -> str:
    if replayed == live:
        return "agree"
    if replayed in _DECIDED and live in _DECIDED:
        return "disagree"
    return "incomplete"


# ---------------------------------------------------------------------------
# the lab
# ---------------------------------------------------------------------------


def waterfall(corpus: Sequence[Dict]) -> Dict:
    """The funnel-loss report of a corpus as CAPTURED: loss reasons
    overall and restricted to host-WON (sat) queries, origins,
    shape-bucket population, per-engine live verdicts."""
    losses: Dict[str, int] = {}
    losses_sat: Dict[str, int] = {}
    origins: Dict[str, int] = {}
    buckets: Dict[str, int] = {}
    verdicts: Dict[str, int] = {}
    for artifact in corpus:
        verdict = artifact.get("verdict", "unknown")
        verdicts[verdict] = verdicts.get(verdict, 0) + 1
        origin = artifact.get("origin", "?")
        origins[origin] = origins.get(origin, 0) + 1
        reason = artifact.get("loss_reason")
        if reason:
            losses[reason] = losses.get(reason, 0) + 1
            if verdict == "sat":
                losses_sat[reason] = losses_sat.get(reason, 0) + 1
        bucket = artifact.get("bucket")
        key = (
            "n{nodes}/c{consts}/r{roots}/v{vars}/L{limbs}".format(**bucket)
            if bucket
            else artifact.get("compile_loss") or "uncompiled"
        )
        buckets[key] = buckets.get(key, 0) + 1
    return {
        "queries": len(corpus),
        "live_verdicts": verdicts,
        "origins": origins,
        "buckets": buckets,
        "loss_waterfall": losses,
        "loss_waterfall_sat": losses_sat,
    }


def replay_corpus(
    corpus: Sequence[Dict],
    engines: Sequence[str] = ("host", "device"),
    timeout_ms: int = 10_000,
    candidates: int = 64,
    steps: int = 512,
) -> Dict:
    """Re-run every artifact against `engines`; returns the report
    dict (waterfall + per-engine verdict/wall/agreement tables +
    disagreement details). Capture is disarmed for the duration so the
    replay never mutates the corpus it reads."""
    for engine in engines:
        if engine not in ENGINES:
            raise ValueError(f"unknown engine {engine!r} (have {ENGINES})")
    report = waterfall(corpus)
    report["schema_version"] = REPORT_SCHEMA_VERSION
    report["engines"] = list(engines)
    tables: Dict[str, Dict] = {
        engine: {
            "verdicts": {},
            "wall_s": 0.0,
            "agreement": {"agree": 0, "disagree": 0, "incomplete": 0},
        }
        for engine in engines
    }
    disagreements: List[Dict] = []

    prev_capture = querylog.capture_dir()
    querylog.configure_capture(None)
    try:
        for artifact in corpus:
            live = artifact.get("verdict", "unknown")
            try:
                lowered = _rebuild(artifact)
            except Exception as why:
                log.warning(
                    "artifact %s did not rebuild: %s", artifact["sha"], why
                )
                for engine in engines:
                    table = tables[engine]
                    table["verdicts"][ERROR] = (
                        table["verdicts"].get(ERROR, 0) + 1
                    )
                    table["agreement"]["incomplete"] += 1
                continue
            row = {"sha": artifact["sha"], "live": live}
            for engine in engines:
                t0 = time.perf_counter()
                try:
                    if engine == "host":
                        verdict = _replay_host(lowered, timeout_ms)
                    elif engine == "device":
                        verdict, _loss = _replay_device(
                            lowered, candidates, steps
                        )
                    else:
                        verdict = _replay_race(lowered, timeout_ms)
                except Exception as why:
                    log.debug(
                        "engine %s failed on %s: %s",
                        engine, artifact["sha"], why, exc_info=True,
                    )
                    verdict = ERROR
                wall = time.perf_counter() - t0
                table = tables[engine]
                table["verdicts"][verdict] = (
                    table["verdicts"].get(verdict, 0) + 1
                )
                table["wall_s"] += wall
                outcome = _classify(live, verdict)
                table["agreement"][outcome] += 1
                row[engine] = verdict
                if outcome == "disagree":
                    row["disagree"] = True
            if row.get("disagree") and len(disagreements) < 32:
                disagreements.append(row)
    finally:
        querylog.configure_capture(prev_capture)

    for engine, table in tables.items():
        table["wall_s"] = round(table["wall_s"], 3)
        n = len(corpus)
        table["agreement_pct"] = (
            round(100.0 * table["agreement"]["agree"] / n, 1) if n else 100.0
        )
    report["replay"] = tables
    report["disagreements"] = disagreements
    return report


def run(
    corpus_dir: str,
    mode: str = "replay",
    engines: Sequence[str] = ("host", "device"),
    timeout_ms: int = 10_000,
    candidates: int = 64,
    steps: int = 512,
    reason: Optional[str] = None,
    origin: Optional[str] = None,
    shard: Optional[str] = None,
) -> Dict:
    """Load + filter + shard a corpus, then replay (or just report)."""
    corpus = querylog.load_corpus(corpus_dir, reason=reason, origin=origin)
    corpus = shard_corpus(corpus, parse_shard(shard))
    if mode == "report":
        report = waterfall(corpus)
        report["schema_version"] = REPORT_SCHEMA_VERSION
    else:
        report = replay_corpus(
            corpus,
            engines=engines,
            timeout_ms=timeout_ms,
            candidates=candidates,
            steps=steps,
        )
    report["corpus_dir"] = corpus_dir
    report["mode"] = mode
    if reason or origin:
        report["filter"] = {"reason": reason, "origin": origin}
    if shard:
        report["shard"] = shard
    return report


def render_text(report: Dict) -> str:
    """The human view: waterfall + agreement tables."""
    lines = [
        "solverlab: {queries} quer{y} from {dir}".format(
            queries=report["queries"],
            y="y" if report["queries"] == 1 else "ies",
            dir=report.get("corpus_dir", "?"),
        )
    ]
    if report.get("filter"):
        lines.append(f"  filter: {report['filter']}")
    if report.get("shard"):
        lines.append(f"  shard: {report['shard']}")
    lines.append("  live verdicts: " + _fmt_counts(report["live_verdicts"]))
    lines.append("  origins:       " + _fmt_counts(report["origins"]))
    lines.append("  loss waterfall (device-lost verdicts):")
    losses = report["loss_waterfall"]
    sat_losses = report.get("loss_waterfall_sat", {})
    for reason in sorted(losses, key=losses.get, reverse=True):
        lines.append(
            f"    {reason:<22} {losses[reason]:>6}"
            f"   (host-won: {sat_losses.get(reason, 0)})"
        )
    if not losses:
        lines.append("    (none recorded)")
    lines.append("  shape buckets: " + _fmt_counts(report["buckets"]))
    for engine, table in (report.get("replay") or {}).items():
        agreement = table["agreement"]
        lines.append(
            f"  engine {engine:<7} verdicts "
            f"{_fmt_counts(table['verdicts'])}  wall {table['wall_s']}s"
        )
        lines.append(
            f"         {'':<7} agreement {table['agreement_pct']}% "
            f"(agree {agreement['agree']} / disagree "
            f"{agreement['disagree']} / incomplete "
            f"{agreement['incomplete']})"
        )
    for row in report.get("disagreements") or []:
        lines.append(f"  DISAGREE {row}")
    return "\n".join(lines)


def _fmt_counts(table: Dict[str, int]) -> str:
    if not table:
        return "(none)"
    return " ".join(
        f"{key}={table[key]}"
        for key in sorted(table, key=table.get, reverse=True)
    )
