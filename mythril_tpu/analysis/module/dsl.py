"""Building blocks shared by every detection module.

The reference repeats the same scaffolding in all 14 modules
(mythril/analysis/module/modules/*): dedupe on the instruction
address, run the analysis, collect issues or potential issues, and
fill the same eight Issue fields from the state. Here that scaffolding
exists once:

  * `ImmediateDetector` — CALLBACK module that finishes its solving in
    the hook and reports `Issue`s directly.
  * `DeferredDetector` — CALLBACK module that pre-solves only a cheap
    property and parks a `PotentialIssue` on the state; the engine
    validates it at transaction end (two-phase flow,
    analysis/potential_issues.py).
  * `found_at(state)` — the Issue/PotentialIssue fields every detector
    copies out of the state.
  * `attacker_transactions(state)` — the "every message call comes
    from the attacker" constraint set detectors share.

Detector hooks receive states one at a time from the host engine but
whole lane vectors from the batched device engine — both arrive
through the HookBus opcode channels, so a module written against this
base runs on either engine unchanged.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.report import Issue
from mythril_tpu.exceptions import UnsatError
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.ethereum.transaction.symbolic import ACTORS
from mythril_tpu.laser.ethereum.transaction.transaction_models import (
    ContractCreationTransaction,
)
from mythril_tpu.laser.smt.bool import And

log = logging.getLogger(__name__)

__all__ = [
    "ACTORS",
    "DeferredDetector",
    "DetectionModule",
    "EntryPoint",
    "ImmediateDetector",
    "Issue",
    "PotentialIssue",
    "UnsatError",
    "attacker_transactions",
    "found_at",
]


def found_at(state: GlobalState, address: Optional[int] = None) -> dict:
    """The site-description fields shared by Issue and PotentialIssue,
    read off the offending state."""
    env = state.environment
    return dict(
        contract=env.active_account.contract_name,
        function_name=env.active_function_name,
        address=(
            address
            if address is not None
            else state.get_current_instruction()["address"]
        ),
        bytecode=env.code.bytecode,
    )


def gas_range(state: GlobalState) -> tuple:
    return (state.mstate.min_gas_used, state.mstate.max_gas_used)


def attacker_transactions(state: GlobalState, tie_origin: bool = False) -> list:
    """Constraints pinning every message call in the sequence to the
    attacker (optionally also requiring caller == origin, i.e. an EOA
    sender)."""
    out = []
    for tx in state.world_state.transaction_sequence:
        if isinstance(tx, ContractCreationTransaction):
            continue
        if tie_origin:
            out.append(And(tx.caller == ACTORS.attacker, tx.caller == tx.origin))
        else:
            out.append(tx.caller == ACTORS.attacker)
    return out


class ImmediateDetector(DetectionModule):
    """Solves its property in the hook and emits finished Issues.

    Subclasses implement `_analyze_state(state) -> List[Issue]`; the
    dedupe-by-address guard and issue collection live here. Set
    `dedupe = False` to analyze every hit of the same instruction.
    """

    entry_point = EntryPoint.CALLBACK
    dedupe = True

    def _execute(self, state: GlobalState) -> None:
        if self.dedupe and state.get_current_instruction()["address"] in self.cache:
            return
        found = self._analyze_state(state)
        for issue in found:
            self.cache.add(issue.address)
        self.issues.extend(found)

    def _analyze_state(self, state: GlobalState) -> List[Issue]:
        raise NotImplementedError


class DeferredDetector(DetectionModule):
    """Pre-solves a cheap property and parks PotentialIssues on the
    state for end-of-transaction validation."""

    entry_point = EntryPoint.CALLBACK
    dedupe = True

    def _execute(self, state: GlobalState) -> None:
        if self.dedupe and state.get_current_instruction()["address"] in self.cache:
            return
        found = self._analyze_state(state)
        get_potential_issues_annotation(state).potential_issues.extend(found)

    def _analyze_state(self, state: GlobalState) -> List[PotentialIssue]:
        raise NotImplementedError
