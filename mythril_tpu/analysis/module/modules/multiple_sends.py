"""SWC-113: multiple external calls in one transaction.

Covers mythril/analysis/module/modules/multiple_sends.py.
"""

from __future__ import annotations

import logging
from copy import copy
from typing import List

from mythril_tpu.analysis.module.dsl import (
    ImmediateDetector,
    Issue,
    UnsatError,
    found_at,
    gas_range,
)
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import MULTIPLE_SENDS
from mythril_tpu.laser.ethereum.state.annotation import StateAnnotation
from mythril_tpu.laser.ethereum.state.global_state import GlobalState

log = logging.getLogger(__name__)

CALL_OPS = ("CALL", "DELEGATECALL", "STATICCALL", "CALLCODE")

REMEDIATION = (
    "This call is executed following another call within the same transaction. It is possible "
    "that the call never gets executed if a prior call fails permanently. This might be caused "
    "intentionally by a malicious callee. If possible, refactor the code such that each transaction "
    "only executes one external call or "
    "make sure that all callees can be trusted (i.e. they’re part of your own codebase)."
)


class MultipleSendsAnnotation(StateAnnotation):
    def __init__(self) -> None:
        self.call_offsets: List[int] = []

    def __copy__(self):
        twin = MultipleSendsAnnotation()
        twin.call_offsets = copy(self.call_offsets)
        return twin


def _call_trace(state: GlobalState) -> List[int]:
    tracker = next(iter(state.get_annotations(MultipleSendsAnnotation)), None)
    if tracker is None:
        tracker = MultipleSendsAnnotation()
        state.annotate(tracker)
    return tracker.call_offsets


class MultipleSends(ImmediateDetector):
    """Checks for multiple sends in a single transaction."""

    name = "Multiple external calls in the same transaction"
    swc_id = MULTIPLE_SENDS
    description = "Check for multiple sends in a single transaction"
    pre_hooks = list(CALL_OPS) + ["RETURN", "STOP"]

    def _analyze_state(self, state: GlobalState) -> list:
        instruction = state.get_current_instruction()
        offsets = _call_trace(state)

        if instruction["opcode"] in CALL_OPS:
            offsets.append(instruction["address"])
            return []

        # RETURN/STOP: the second and later calls are the finding
        for repeat_offset in offsets[1:]:
            try:
                witness = get_transaction_sequence(
                    state, state.world_state.constraints
                )
            except UnsatError:
                continue
            return [
                Issue(
                    swc_id=MULTIPLE_SENDS,
                    title="Multiple Calls in a Single Transaction",
                    severity="Low",
                    description_head=(
                        "Multiple calls are executed in the same transaction."
                    ),
                    description_tail=REMEDIATION,
                    gas_used=gas_range(state),
                    transaction_sequence=witness,
                    **found_at(state, address=repeat_offset),
                )
            ]
        return []


detector = MultipleSends()
