"""SWC-107 (reentrancy surface): call to a user-supplied address with
unrestricted gas.

Covers mythril/analysis/module/modules/external_calls.py.
"""

from __future__ import annotations

import logging
from copy import copy

from mythril_tpu.analysis import solver
from mythril_tpu.analysis.module.dsl import (
    ACTORS,
    DeferredDetector,
    PotentialIssue,
    UnsatError,
    found_at,
)
from mythril_tpu.analysis.swc_data import REENTRANCY
from mythril_tpu.laser.ethereum.natives import PRECOMPILE_COUNT
from mythril_tpu.laser.ethereum.state.constraints import Constraints
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.smt import BitVec, Or, UGT, symbol_factory

log = logging.getLogger(__name__)

REMEDIATION = (
    "An external message call to an address specified by the caller is executed. Note that "
    "the callee account might contain arbitrary code and could re-enter any function "
    "within this contract. Reentering the contract in an intermediate state may lead to "
    "unexpected behaviour. Make sure that no state modifications "
    "are executed after this call and/or reentrancy guards are in place."
)


def _is_precompile_call(global_state: GlobalState) -> bool:
    to: BitVec = global_state.mstate.stack[-2]
    outside_precompiles = copy(global_state.world_state.constraints) + [
        Or(
            to < symbol_factory.BitVecVal(1, 256),
            to > symbol_factory.BitVecVal(PRECOMPILE_COUNT, 256),
        )
    ]
    try:
        solver.get_model(outside_precompiles)
        return False
    except UnsatError:
        return True


class ExternalCalls(DeferredDetector):
    """Searches for low-level calls that forward all gas to an
    attacker-controlled callee."""

    name = "External call to another contract"
    swc_id = REENTRANCY
    description = (
        "Search for external calls with unrestricted gas to a"
        " user-specified address."
    )
    pre_hooks = ["CALL"]
    dedupe = False  # the reference re-analyzes every hit

    def _analyze_state(self, state: GlobalState) -> list:
        from mythril_tpu.analysis.prepass import device_already_proved

        if device_already_proved(state, REENTRANCY):
            # a device lane concretely called the attacker from this
            # site with forwarded gas; the banked witness carries it
            return []
        gas, target = state.mstate.stack[-1], state.mstate.stack[-2]

        attack_property = Constraints(
            [
                UGT(gas, symbol_factory.BitVecVal(2300, 256)),
                target == ACTORS.attacker,
            ]
        )
        try:
            solver.get_transaction_sequence(
                state, attack_property + state.world_state.constraints
            )
        except UnsatError:
            log.debug("[EXTERNAL_CALLS] No model found.")
            return []

        return [
            PotentialIssue(
                swc_id=REENTRANCY,
                title="External Call To User-Supplied Address",
                severity="Low",
                description_head=(
                    "A call to a user-supplied address is executed."
                ),
                description_tail=REMEDIATION,
                constraints=attack_property,
                detector=self,
                **found_at(state),
            )
        ]


detector = ExternalCalls()
