"""SWC-101: integer overflow / underflow.

Covers mythril/analysis/module/modules/integer.py. Arithmetic
pre-hooks annotate the result with the negated no-overflow predicate;
use-site hooks (SSTORE/JUMPI/CALL/RETURN) promote those taints into a
state annotation ("the wrapped value was actually used"); at
transaction end every collected wrap condition is solved against the
full path, with a satisfiability cache keyed on the overflowing state.
"""

from __future__ import annotations

import logging
from copy import copy
from math import ceil, log2
from typing import Callable, Dict, List, Set

from mythril_tpu.analysis import solver
from mythril_tpu.analysis.module.dsl import (
    DetectionModule,
    EntryPoint,
    Issue,
    UnsatError,
    found_at,
    gas_range,
)
from mythril_tpu.analysis.swc_data import INTEGER_OVERFLOW_AND_UNDERFLOW
from mythril_tpu.laser.ethereum.state.annotation import StateAnnotation
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.smt import (
    And,
    BVAddNoOverflow,
    BVMulNoOverflow,
    BVSubNoUnderflow,
    BitVec,
    Bool,
    Expression,
    If,
    Not,
    symbol_factory,
)

log = logging.getLogger(__name__)

REMEDIATION = (
    "It is possible to cause an integer overflow or underflow in the"
    " arithmetic operation. Prevent this by constraining inputs using"
    " the require() statement or use the OpenZeppelin SafeMath"
    " library for integer arithmetic operations. Refer to the"
    " transaction trace generated for this issue to reproduce the"
    " issue."
)


class OverUnderflowAnnotation:
    """Symbol annotation: this value may have wrapped around."""

    def __init__(
        self, overflowing_state: GlobalState, operator: str, constraint: Bool
    ) -> None:
        self.overflowing_state = overflowing_state
        self.operator = operator
        self.constraint = constraint

    def __deepcopy__(self, memodict={}):
        return copy(self)


class OverUnderflowStateAnnotation(StateAnnotation):
    """State annotation: wraps both possible and used on this path.

    The taint collection is an insertion-ordered identity set (a dict
    used for its keys): annotation objects hash by identity, so a
    plain `set` iterates in memory-address order — which varies run to
    run with allocator layout, letting a different taint win the
    per-address issue dedupe and drift the reported witness. Dict key
    order is insertion order: deterministic."""

    def __init__(self) -> None:
        self.overflowing_state_annotations: Dict[
            OverUnderflowAnnotation, None
        ] = {}

    def __copy__(self):
        twin = OverUnderflowStateAnnotation()
        twin.overflowing_state_annotations = copy(
            self.overflowing_state_annotations
        )
        return twin


def _flow_annotation(state: GlobalState) -> OverUnderflowStateAnnotation:
    existing = next(
        iter(state.get_annotations(OverUnderflowStateAnnotation)), None
    )
    if existing is not None:
        return existing
    fresh = OverUnderflowStateAnnotation()
    state.annotate(fresh)
    return fresh


def _word_at(stack, index) -> BitVec:
    """stack[index] as a BitVec, converting in place if needed."""
    value = stack[index]
    if isinstance(value, BitVec):
        return value
    if isinstance(value, Bool):
        return If(value, 1, 0)
    stack[index] = symbol_factory.BitVecVal(value, 256)
    return stack[index]


def _promote_taints(state: GlobalState, value) -> None:
    """Move wrap taints from a used value onto the state."""
    if not isinstance(value, Expression):
        return
    flow = _flow_annotation(state)
    for taint in value.annotations:
        if isinstance(taint, OverUnderflowAnnotation):
            flow.overflowing_state_annotations[taint] = None


class IntegerArithmetics(DetectionModule):
    """Searches for integer over- and underflows."""

    name = "Integer overflow or underflow"
    swc_id = INTEGER_OVERFLOW_AND_UNDERFLOW
    description = (
        "For every SUB instruction, check if there's a possible state "
        "where op1 > op0. For every ADD, MUL instruction, check if "
        "there's a possible state where op1 + op0 > 2^32 - 1"
    )
    entry_point = EntryPoint.CALLBACK
    pre_hooks = [
        "ADD",
        "MUL",
        "EXP",
        "SUB",
        "SSTORE",
        "JUMPI",
        "STOP",
        "RETURN",
        "CALL",
    ]

    #: wrap predicates per arithmetic opcode
    WRAP_RULES = {
        "ADD": ("addition", lambda a, b: Not(BVAddNoOverflow(a, b, False))),
        "MUL": ("multiplication", lambda a, b: Not(BVMulNoOverflow(a, b, False))),
        "SUB": ("subtraction", lambda a, b: Not(BVSubNoUnderflow(a, b, False))),
    }

    def __init__(self) -> None:
        super().__init__()
        self._known_sat: Set[GlobalState] = set()
        self._known_unsat: Set[GlobalState] = set()

    def reset_module(self):
        super().reset_module()
        self._known_sat = set()
        self._known_unsat = set()

    # -- dispatch ------------------------------------------------------
    def _execute(self, state: GlobalState) -> None:
        if state.get_current_instruction()["address"] in self.cache:
            return
        opcode = state.get_current_instruction()["opcode"]
        routes: Dict[str, List[Callable]] = {
            "ADD": [self._taint_arith],
            "SUB": [self._taint_arith],
            "MUL": [self._taint_arith],
            "EXP": [self._taint_exp],
            "SSTORE": [self._use_sstore],
            "JUMPI": [self._use_jumpi],
            "CALL": [self._use_call],
            "RETURN": [self._use_return, self._finalize],
            "STOP": [self._finalize],
        }
        for step in routes[opcode]:
            step(state)

    # -- taint producers -----------------------------------------------
    def _taint_arith(self, state: GlobalState) -> None:
        opcode = state.get_current_instruction()["opcode"]
        operator, predicate = self.WRAP_RULES[opcode]
        stack = state.mstate.stack
        a, b = _word_at(stack, -1), _word_at(stack, -2)
        a.annotate(OverUnderflowAnnotation(state, operator, predicate(a, b)))

    def _taint_exp(self, state: GlobalState) -> None:
        stack = state.mstate.stack
        base, power = _word_at(stack, -1), _word_at(stack, -2)
        if base.symbolic and power.symbolic:
            wraps = And(
                power > symbol_factory.BitVecVal(256, 256),
                base > symbol_factory.BitVecVal(1, 256),
            )
        elif power.symbolic:
            if base.value < 2:
                return
            wraps = power >= symbol_factory.BitVecVal(
                ceil(256 / log2(base.value)), 256
            )
        elif base.symbolic:
            if power.value == 0:
                return
            wraps = base >= symbol_factory.BitVecVal(
                2 ** ceil(256 / power.value), 256
            )
        else:
            wraps = base.value**power.value >= 2**256
        base.annotate(OverUnderflowAnnotation(state, "exponentiation", wraps))

    # -- taint consumers -----------------------------------------------
    @staticmethod
    def _use_sstore(state: GlobalState) -> None:
        _promote_taints(state, state.mstate.stack[-2])

    @staticmethod
    def _use_jumpi(state: GlobalState) -> None:
        _promote_taints(state, state.mstate.stack[-2])

    @staticmethod
    def _use_call(state: GlobalState) -> None:
        _promote_taints(state, state.mstate.stack[-3])

    @staticmethod
    def _use_return(state: GlobalState) -> None:
        """Taints reachable through the returned memory window."""
        stack = state.mstate.stack
        offset, length = stack[-1], stack[-2]
        for cell in state.mstate.memory[offset : offset + length]:
            _promote_taints(state, cell)

    # -- transaction end -----------------------------------------------
    def _finalize(self, state: GlobalState) -> None:
        from mythril_tpu.analysis.prepass import device_already_proved

        for taint in _flow_annotation(state).overflowing_state_annotations:
            origin = taint.overflowing_state

            if origin in self._known_unsat:
                continue
            if device_already_proved(origin, INTEGER_OVERFLOW_AND_UNDERFLOW):
                # a device lane concretely wrapped at this site and
                # used the result; its banked witness carries the issue
                continue
            if origin not in self._known_sat:
                # cheap pre-check against the origin state's own path
                try:
                    solver.get_model(
                        origin.world_state.constraints + [taint.constraint]
                    )
                    self._known_sat.add(origin)
                except Exception:
                    self._known_unsat.add(origin)
                    continue

            log.debug(
                "Checking overflow in %s at transaction end address %s, "
                "ostate address %s",
                state.get_current_instruction()["opcode"],
                state.get_current_instruction()["address"],
                origin.get_current_instruction()["address"],
            )

            try:
                witness = solver.get_transaction_sequence(
                    state, state.world_state.constraints + [taint.constraint]
                )
            except UnsatError:
                continue

            issue = Issue(
                swc_id=INTEGER_OVERFLOW_AND_UNDERFLOW,
                title="Integer Arithmetic Bugs",
                severity="High",
                description_head="The arithmetic operator can {}.".format(
                    "underflow"
                    if taint.operator == "subtraction"
                    else "overflow"
                ),
                description_tail=REMEDIATION,
                gas_used=gas_range(state),
                transaction_sequence=witness,
                **found_at(origin),
            )
            self.cache.add(origin.get_current_instruction()["address"])
            self.issues.append(issue)


detector = IntegerArithmetics()
