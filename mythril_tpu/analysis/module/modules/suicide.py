"""SWC-106: anyone can SELFDESTRUCT the contract.

Covers mythril/analysis/module/modules/suicide.py — tries the
stronger property first (balance flows to the attacker); falls back to
the weaker killable-by-anyone variant when that is unsat.
"""

from __future__ import annotations

import logging

from mythril_tpu.analysis import solver
from mythril_tpu.analysis.module.dsl import (
    ACTORS,
    ImmediateDetector,
    Issue,
    UnsatError,
    attacker_transactions,
    found_at,
    gas_range,
)
from mythril_tpu.analysis.swc_data import UNPROTECTED_SELFDESTRUCT
from mythril_tpu.laser.ethereum.state.global_state import GlobalState

log = logging.getLogger(__name__)

TAIL_WITH_THEFT = (
    "Any sender can trigger execution of the SELFDESTRUCT instruction to destroy this "
    "contract account and withdraw its balance to an arbitrary address. Review the transaction trace "
    "generated for this issue and make sure that appropriate security controls are in place to prevent "
    "unrestricted access."
)
TAIL_KILL_ONLY = (
    "Any sender can trigger execution of the SELFDESTRUCT instruction to destroy this "
    "contract account. Review the transaction trace generated for this issue and make sure that "
    "appropriate security controls are in place to prevent unrestricted access."
)


class AccidentallyKillable(ImmediateDetector):
    """Checks if the contract can be 'accidentally' killed by anyone."""

    name = "Contract can be accidentally killed by anyone"
    swc_id = UNPROTECTED_SELFDESTRUCT
    description = (
        "Check if the contact can be 'accidentally' killed by anyone. For"
        " kill-able contracts, also check whether it is possible to direct"
        " the contract balance to the attacker."
    )
    pre_hooks = ["SUICIDE"]

    def __init__(self):
        super().__init__()
        self._cache_address = {}

    def _analyze_state(self, state: GlobalState) -> list:
        log.debug(
            "SUICIDE in function %s", state.environment.active_function_name
        )
        # (no device-witness pre-emption here: this module's two-tier
        # property — balance theft before kill-only — is strictly
        # richer than the prepass's reachability witness, so the host
        # solve runs and fire_lasers dedups the weaker device issue)
        beneficiary = state.mstate.stack[-1]
        attacker_only = attacker_transactions(state, tie_origin=True)
        base = state.world_state.constraints + attacker_only

        try:
            try:
                witness = solver.get_transaction_sequence(
                    state, base + [beneficiary == ACTORS.attacker]
                )
                tail = TAIL_WITH_THEFT
            except UnsatError:
                witness = solver.get_transaction_sequence(state, base)
                tail = TAIL_KILL_ONLY
        except UnsatError:
            log.debug("No model found")
            return []

        return [
            Issue(
                swc_id=UNPROTECTED_SELFDESTRUCT,
                title="Unprotected Selfdestruct",
                severity="High",
                description_head=(
                    "Any sender can cause the contract to self-destruct."
                ),
                description_tail=tail,
                transaction_sequence=witness,
                gas_used=gas_range(state),
                **found_at(state),
            )
        ]


detector = AccidentallyKillable()
