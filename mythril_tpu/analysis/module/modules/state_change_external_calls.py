"""SWC-107: state access after an external call (reentrancy pattern).

Covers mythril/analysis/module/modules/state_change_external_calls.py.
A gas-forwarding external call annotates the path; any later storage
access (or value-bearing call) on that path becomes a potential issue
validated at transaction end.
"""

from __future__ import annotations

import logging
from copy import copy
from typing import List, Optional

from mythril_tpu.analysis import solver
from mythril_tpu.analysis.module.dsl import (
    DeferredDetector,
    DetectionModule,
    PotentialIssue,
    UnsatError,
    found_at,
)
from mythril_tpu.analysis.swc_data import REENTRANCY
from mythril_tpu.laser.ethereum.state.annotation import StateAnnotation
from mythril_tpu.laser.ethereum.state.constraints import Constraints
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.smt import BitVec, Or, UGT, symbol_factory

log = logging.getLogger(__name__)

CALL_OPS = ("CALL", "DELEGATECALL", "CALLCODE")
STATE_OPS = ("SSTORE", "SLOAD", "CREATE", "CREATE2")

# shared with analysis/evidence.py — device-evidence SWC-107 issues must
# carry byte-identical text so report dedupe collapses the two paths
DESCRIPTION_TAIL_TEMPLATE = (
    "The contract account state is accessed after an external call to a {} address. "
    "To prevent reentrancy issues, consider accessing the state only before the call, especially if the "
    "callee is untrusted. Alternatively, a reentrancy lock can be used to prevent "
    "untrusted callees from re-entering the contract in an intermediate state."
)

ATTACKER_ADDRESS = 0xDEADBEEFDEADBEEFDEADBEEFDEADBEEFDEADBEEF


def _forwarding_call_constraints(call_state: GlobalState) -> Constraints:
    """The call forwards real gas to a non-precompile callee."""
    gas = call_state.mstate.stack[-1]
    to = call_state.mstate.stack[-2]
    return Constraints(
        [
            UGT(gas, symbol_factory.BitVecVal(2300, 256)),
            Or(
                to > symbol_factory.BitVecVal(16, 256),
                to == symbol_factory.BitVecVal(0, 256),
            ),
        ]
    )


class StateChangeCallsAnnotation(StateAnnotation):
    """Marks a path that performed a gas-forwarding external call."""

    def __init__(self, call_state: GlobalState, user_defined_address: bool):
        self.call_state = call_state
        self.state_change_states: List[GlobalState] = []
        self.user_defined_address = user_defined_address

    def __copy__(self):
        twin = StateChangeCallsAnnotation(
            self.call_state, self.user_defined_address
        )
        twin.state_change_states = self.state_change_states[:]
        return twin

    def get_issue(
        self, global_state: GlobalState, detector: DetectionModule
    ) -> Optional[PotentialIssue]:
        if not self.state_change_states:
            return None

        call_constraints = _forwarding_call_constraints(self.call_state)
        if self.user_defined_address:
            call_constraints += [
                self.call_state.mstate.stack[-2] == ATTACKER_ADDRESS
            ]

        try:
            solver.get_transaction_sequence(
                global_state,
                call_constraints + global_state.world_state.constraints,
            )
        except UnsatError:
            return None

        here = global_state.get_current_instruction()
        log.debug(
            "[EXTERNAL_CALLS] Detected state changes at address: %s",
            here["address"],
        )
        access_kind = "Read of" if here["opcode"] == "SLOAD" else "Write to"
        address_kind = "user defined" if self.user_defined_address else "fixed"

        return PotentialIssue(
            title="State access after external call",
            severity="Medium" if self.user_defined_address else "Low",
            description_head=(
                f"{access_kind} persistent state following external call"
            ),
            description_tail=DESCRIPTION_TAIL_TEMPLATE.format(address_kind),
            swc_id=REENTRANCY,
            constraints=call_constraints,
            detector=detector,
            **found_at(global_state),
        )


class StateChangeAfterCall(DeferredDetector):
    """Searches for state changes after gas-forwarding external calls."""

    name = "State change after an external call"
    swc_id = REENTRANCY
    description = (
        "Check whether the account state is accessed after the execution"
        " of an external call"
    )
    pre_hooks = list(CALL_OPS + STATE_OPS)

    def _analyze_state(self, state: GlobalState) -> List[PotentialIssue]:
        open_calls = list(state.get_annotations(StateChangeCallsAnnotation))
        opcode = state.get_current_instruction()["opcode"]

        if opcode in STATE_OPS:
            for call in open_calls:
                call.state_change_states.append(state)
        elif opcode in CALL_OPS:
            # a value-bearing call is itself a balance mutation
            if self._value_may_flow(state.mstate.stack[-3], state):
                for call in open_calls:
                    call.state_change_states.append(state)
            self._register_call(state)

        findings = []
        for call in open_calls:
            if not call.state_change_states:
                continue
            issue = call.get_issue(state, self)
            if issue:
                findings.append(issue)
        return findings

    @staticmethod
    def _register_call(state: GlobalState) -> None:
        """Annotate the path if this call forwards gas; classify the
        callee address as attacker-choosable or fixed."""
        base = copy(state.world_state.constraints)
        try:
            solver.get_model(base + _forwarding_call_constraints(state))
        except UnsatError:
            return
        try:
            solver.get_model(
                base + [state.mstate.stack[-2] == ATTACKER_ADDRESS]
            )
            state.annotate(StateChangeCallsAnnotation(state, True))
        except UnsatError:
            state.annotate(StateChangeCallsAnnotation(state, False))

    @staticmethod
    def _value_may_flow(value: BitVec, state: GlobalState) -> bool:
        if not value.symbolic:
            assert value.value is not None
            return value.value > 0
        try:
            solver.get_model(
                copy(state.world_state.constraints)
                + [value > symbol_factory.BitVecVal(0, 256)]
            )
            return True
        except UnsatError:
            return False


detector = StateChangeAfterCall()
