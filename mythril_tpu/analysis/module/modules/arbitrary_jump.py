"""SWC-127: jump to a caller-controlled destination.

Covers mythril/analysis/module/modules/arbitrary_jump.py.
"""

from __future__ import annotations

import logging

from mythril_tpu.analysis.module.dsl import (
    ImmediateDetector,
    Issue,
    UnsatError,
    found_at,
    gas_range,
)
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import ARBITRARY_JUMP
from mythril_tpu.laser.ethereum.state.global_state import GlobalState

log = logging.getLogger(__name__)

REMEDIATION = (
    "It is possible to redirect the control flow to arbitrary locations in the code. "
    "This may allow an attacker to bypass security controls or manipulate the business logic of the "
    "smart contract. Avoid using low-level-operations and assembly to prevent this issue."
)


class ArbitraryJump(ImmediateDetector):
    """Flags JUMP/JUMPI whose destination stays symbolic (and is
    therefore attacker-influenceable)."""

    name = "Caller can redirect execution to arbitrary bytecode locations"
    swc_id = ARBITRARY_JUMP
    description = "Search for jumps to arbitrary locations in the bytecode"
    pre_hooks = ["JUMP", "JUMPI"]

    def _execute(self, state: GlobalState) -> None:
        # reference quirk kept: the cache is consulted but never fed,
        # so repeated hits re-report (golden outputs depend on it)
        if state.get_current_instruction()["address"] in self.cache:
            return
        self.issues.extend(self._analyze_state(state))

    def _analyze_state(self, state: GlobalState) -> list:
        if state.mstate.stack[-1].symbolic is False:
            return []
        try:
            witness = get_transaction_sequence(
                state, state.world_state.constraints
            )
        except UnsatError:
            return []
        return [
            Issue(
                swc_id=ARBITRARY_JUMP,
                title="Jump to an arbitrary instruction",
                severity="High",
                description_head=(
                    "The caller can redirect execution to arbitrary bytecode locations."
                ),
                description_tail=REMEDIATION,
                gas_used=gas_range(state),
                transaction_sequence=witness,
                **found_at(state),
            )
        ]


detector = ArbitraryJump()
