"""SWC-105: anyone can profitably withdraw Ether.

Covers mythril/analysis/module/modules/ether_thief.py. The property:
a valid end state exists where the attacker's balance exceeds their
starting balance, with the attacker as an EOA sender.
"""

from __future__ import annotations

import logging
from copy import copy

from mythril_tpu.analysis import solver
from mythril_tpu.analysis.module.dsl import (
    ACTORS,
    DeferredDetector,
    PotentialIssue,
    UnsatError,
    found_at,
)
from mythril_tpu.analysis.swc_data import UNPROTECTED_ETHER_WITHDRAWAL
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.smt import UGT

log = logging.getLogger(__name__)

REMEDIATION = (
    "Arbitrary senders other than the contract creator can profitably extract Ether "
    "from the contract account. Verify the business logic carefully and make sure that appropriate "
    "security controls are in place to prevent unexpected loss of funds."
)


class EtherThief(DeferredDetector):
    """Searches for cases where Ether can be withdrawn to a
    user-specified address."""

    name = "Any sender can withdraw ETH from the contract account"
    swc_id = UNPROTECTED_ETHER_WITHDRAWAL
    description = (
        "Search for cases where Ether can be withdrawn to a user-specified"
        " address. An issue is reported if there is a valid end state where"
        " the attacker has successfully increased their Ether balance."
    )
    post_hooks = ["CALL", "STATICCALL"]

    def _analyze_state(self, state: GlobalState) -> list:
        from mythril_tpu.analysis.prepass import device_already_proved

        if device_already_proved(
            state,
            UNPROTECTED_ETHER_WITHDRAWAL,
            address=state.get_current_instruction()["address"] - 1,
        ):
            # a device lane concretely sent value to the attacker from
            # this call site; the banked witness carries the issue
            return []
        state = copy(state)
        world = state.world_state

        attacker_profits = copy(world.constraints) + [
            UGT(
                world.balances[ACTORS.attacker],
                world.starting_balances[ACTORS.attacker],
            ),
            state.environment.sender == ACTORS.attacker,
            state.current_transaction.caller
            == state.current_transaction.origin,
        ]

        try:
            # pre-solve: raise a potential issue only when the profit
            # property is satisfiable on this path
            solver.get_model(attacker_profits)
        except UnsatError:
            return []

        return [
            PotentialIssue(
                swc_id=UNPROTECTED_ETHER_WITHDRAWAL,
                title="Unprotected Ether Withdrawal",
                severity="High",
                description_head=(
                    "Any sender can withdraw Ether from the contract account."
                ),
                description_tail=REMEDIATION,
                detector=self,
                constraints=attacker_profits,
                # post hook: report the offset of the CALL itself
                **found_at(
                    state,
                    address=state.get_current_instruction()["address"] - 1,
                ),
            )
        ]


detector = EtherThief()
