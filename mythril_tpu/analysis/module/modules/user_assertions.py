"""SWC-110: user-supplied assertion messages.

Reference parity: mythril/analysis/module/modules/user_assertions.py
:30-122 — watches for `emit AssertionFailed(string)` LOG1 topics and
the MythX mstore marker pattern. The ABI string decode is done inline
(the reference pulls in eth_abi for this one call).
"""

from __future__ import annotations

import logging

from mythril_tpu.analysis import solver
from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.swc_data import ASSERT_VIOLATION
from mythril_tpu.exceptions import UnsatError
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.smt import Extract

log = logging.getLogger(__name__)

assertion_failed_hash = (
    0xB42604CB105A16C8F6DB8A41E6B00C0C1B4826465E8BC504B3EB3E88B3E6A4A0
)

mstore_pattern = "0xcafecafecafecafecafecafecafecafecafecafecafecafecafecafecafe"


def _decode_abi_string(data: bytes) -> str:
    """Decode a single ABI-encoded string payload (length word followed
    by utf-8 bytes)."""
    if len(data) < 32:
        raise ValueError("short ABI string")
    length = int.from_bytes(data[:32], "big")
    return data[32 : 32 + length].decode("utf8")


class UserAssertions(DetectionModule):
    """Searches for user-supplied exceptions:
    emit AssertionFailed("Error")."""

    name = "A user-defined assertion has been triggered"
    swc_id = ASSERT_VIOLATION
    description = (
        "Search for reachable user-supplied exceptions. Report a warning if"
        " a log message is emitted: 'emit AssertionFailed(string)'"
    )
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["LOG1", "MSTORE"]

    def _execute(self, state: GlobalState) -> None:
        issues = self._analyze_state(state)
        for issue in issues:
            self.cache.add(issue.address)
        self.issues.extend(issues)

    def _analyze_state(self, state: GlobalState):
        opcode = state.get_current_instruction()["opcode"]
        message = None
        if opcode == "MSTORE":
            value = state.mstate.stack[-2]
            if value.symbolic:
                return []
            if mstore_pattern not in hex(value.value)[:126]:
                return []
            message = "Failed property id {}".format(Extract(15, 0, value).value)
        else:
            topic, size, mem_start = state.mstate.stack[-3:]
            if topic.symbolic or topic.value != assertion_failed_hash:
                return []
            if not mem_start.symbolic and not size.symbolic:
                try:
                    payload = bytes(
                        b if isinstance(b, int) else (b.value or 0)
                        for b in state.mstate.memory[
                            mem_start.value + 32 : mem_start.value + size.value
                        ]
                    )
                    message = _decode_abi_string(payload)
                except Exception:
                    pass

        try:
            transaction_sequence = solver.get_transaction_sequence(
                state, state.world_state.constraints
            )
            if message:
                description_tail = (
                    "A user-provided assertion failed with the message '{}'".format(
                        message
                    )
                )
            else:
                description_tail = "A user-provided assertion failed."
            log.debug("User assertion emitted: %s", description_tail)

            address = state.get_current_instruction()["address"]
            issue = Issue(
                contract=state.environment.active_account.contract_name,
                function_name=state.environment.active_function_name,
                address=address,
                swc_id=ASSERT_VIOLATION,
                title="Exception State",
                severity="Medium",
                description_head="A user-provided assertion failed.",
                description_tail=description_tail,
                bytecode=state.environment.code.bytecode,
                transaction_sequence=transaction_sequence,
                gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            )
            return [issue]
        except UnsatError:
            log.debug("no model found")
        return []


detector = UserAssertions()
