"""SWC-110: user-supplied assertion messages.

Covers mythril/analysis/module/modules/user_assertions.py — watches
for `emit AssertionFailed(string)` LOG1 topics and the MythX mstore
marker pattern. The ABI string decode is done inline (the reference
pulls in eth_abi for this one call).
"""

from __future__ import annotations

import logging

from mythril_tpu.analysis import solver
from mythril_tpu.analysis.module.dsl import (
    ImmediateDetector,
    Issue,
    UnsatError,
    found_at,
    gas_range,
)
from mythril_tpu.analysis.swc_data import ASSERT_VIOLATION
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.smt import Extract

log = logging.getLogger(__name__)

ASSERTION_FAILED_TOPIC = (
    0xB42604CB105A16C8F6DB8A41E6B00C0C1B4826465E8BC504B3EB3E88B3E6A4A0
)

MSTORE_MARKER = "0xcafecafecafecafecafecafecafecafecafecafecafecafecafecafecafe"


def _read_abi_string(blob: bytes) -> str:
    """Decode one ABI-encoded string payload (length word + utf-8)."""
    if len(blob) < 32:
        raise ValueError("short ABI string")
    n = int.from_bytes(blob[:32], "big")
    return blob[32 : 32 + n].decode("utf8")


class UserAssertions(ImmediateDetector):
    """Searches for user-supplied exceptions:
    emit AssertionFailed("Error")."""

    name = "A user-defined assertion has been triggered"
    swc_id = ASSERT_VIOLATION
    description = (
        "Search for reachable user-supplied exceptions. Report a warning if"
        " a log message is emitted: 'emit AssertionFailed(string)'"
    )
    pre_hooks = ["LOG1", "MSTORE"]
    dedupe = False  # the reference analyzes every hit

    def _message_from(self, state: GlobalState):
        """The assertion message carried by this LOG1/MSTORE, or None
        when this instruction is not an assertion marker at all
        (signalled by raising LookupError)."""
        if state.get_current_instruction()["opcode"] == "MSTORE":
            word = state.mstate.stack[-2]
            if word.symbolic or MSTORE_MARKER not in hex(word.value)[:126]:
                raise LookupError
            return f"Failed property id {Extract(15, 0, word).value}"

        topic, size, start = state.mstate.stack[-3:]
        if topic.symbolic or topic.value != ASSERTION_FAILED_TOPIC:
            raise LookupError
        if start.symbolic or size.symbolic:
            return None
        try:
            blob = bytes(
                b if isinstance(b, int) else (b.value or 0)
                for b in state.mstate.memory[
                    start.value + 32 : start.value + size.value
                ]
            )
            return _read_abi_string(blob)
        except Exception:
            return None

    def _analyze_state(self, state: GlobalState) -> list:
        try:
            message = self._message_from(state)
        except LookupError:
            return []

        try:
            witness = solver.get_transaction_sequence(
                state, state.world_state.constraints
            )
        except UnsatError:
            log.debug("no model found")
            return []

        if message:
            tail = f"A user-provided assertion failed with the message '{message}'"
        else:
            tail = "A user-provided assertion failed."
        log.debug("User assertion emitted: %s", tail)

        return [
            Issue(
                swc_id=ASSERT_VIOLATION,
                title="Exception State",
                severity="Medium",
                description_head="A user-provided assertion failed.",
                description_tail=tail,
                transaction_sequence=witness,
                gas_used=gas_range(state),
                **found_at(state),
            )
        ]


detector = UserAssertions()
