"""SWC-112: delegatecall to an attacker-supplied address.

Covers mythril/analysis/module/modules/delegatecall.py.
"""

from __future__ import annotations

import logging

from mythril_tpu.analysis.module.dsl import (
    ACTORS,
    DeferredDetector,
    PotentialIssue,
    found_at,
)
from mythril_tpu.analysis.swc_data import DELEGATECALL_TO_UNTRUSTED_CONTRACT
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.ethereum.transaction.transaction_models import (
    ContractCreationTransaction,
)
from mythril_tpu.laser.smt import UGT, symbol_factory

log = logging.getLogger(__name__)

REMEDIATION = (
    "The smart contract delegates execution to a user-supplied address."
    "This could allow an attacker to execute arbitrary code in the context of this contract "
    "account and manipulate the state of the contract account or execute actions on its behalf."
)


class ArbitraryDelegateCall(DeferredDetector):
    """Detects delegatecall to a user-supplied address."""

    name = "Delegatecall to a user-specified address"
    swc_id = DELEGATECALL_TO_UNTRUSTED_CONTRACT
    description = (
        "Check for invocations of delegatecall to a user-supplied address."
    )
    pre_hooks = ["DELEGATECALL"]

    def _analyze_state(self, state: GlobalState) -> list:
        gas, target = state.mstate.stack[-1], state.mstate.stack[-2]
        here = state.get_current_instruction()["address"]

        property_constraints = [
            target == ACTORS.attacker,
            UGT(gas, symbol_factory.BitVecVal(2300, 256)),
            state.new_bitvec(f"retval_{here}", 256) == 1,
        ]
        # every message call in the sequence must come from the attacker
        for tx in state.world_state.transaction_sequence:
            if not isinstance(tx, ContractCreationTransaction):
                property_constraints.append(tx.caller == ACTORS.attacker)

        log.debug(
            "[DELEGATECALL] Detected potential delegatecall to a "
            "user-supplied address: %s",
            here,
        )
        return [
            PotentialIssue(
                swc_id=DELEGATECALL_TO_UNTRUSTED_CONTRACT,
                title="Delegatecall to user-supplied address",
                severity="High",
                description_head=(
                    "The contract delegates execution to another contract "
                    "with a user-supplied address."
                ),
                description_tail=REMEDIATION,
                constraints=property_constraints,
                detector=self,
                **found_at(state),
            )
        ]


detector = ArbitraryDelegateCall()
