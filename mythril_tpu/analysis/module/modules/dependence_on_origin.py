"""SWC-115: control flow depends on tx.origin.

Covers mythril/analysis/module/modules/dependence_on_origin.py — the
ORIGIN post-hook taints the pushed symbol; the JUMPI pre-hook reports
branches decided by a tainted value.
"""

from __future__ import annotations

import logging
from copy import copy

from mythril_tpu.analysis import solver
from mythril_tpu.analysis.module.dsl import (
    ImmediateDetector,
    Issue,
    UnsatError,
    found_at,
    gas_range,
)
from mythril_tpu.analysis.swc_data import TX_ORIGIN_USAGE
from mythril_tpu.laser.ethereum.state.global_state import GlobalState

log = logging.getLogger(__name__)

REMEDIATION = (
    "The tx.origin environment variable has been found to influence a control flow decision. "
    "Note that using tx.origin as a security control might cause a situation where a user "
    "inadvertently authorizes a smart contract to perform an action on their behalf. It is "
    "recommended to use msg.sender instead."
)


class TxOriginAnnotation:
    """Symbol annotation marking a value derived from ORIGIN."""


class TxOrigin(ImmediateDetector):
    """Detects branches that depend on the transaction origin."""

    name = "Control flow depends on tx.origin"
    swc_id = TX_ORIGIN_USAGE
    description = (
        "Check whether control flow decisions are influenced by tx.origin"
    )
    pre_hooks = ["JUMPI"]
    post_hooks = ["ORIGIN"]

    def _analyze_state(self, state: GlobalState) -> list:
        if state.get_current_instruction()["opcode"] != "JUMPI":
            # ORIGIN post-hook: taint the freshly pushed value
            state.mstate.stack[-1].annotate(TxOriginAnnotation())
            return []

        # JUMPI pre-hook: is the branch guard tainted?
        tainted = any(
            isinstance(a, TxOriginAnnotation)
            for a in state.mstate.stack[-2].annotations
        )
        if not tainted:
            return []
        from mythril_tpu.analysis.prepass import device_already_proved

        if device_already_proved(state, TX_ORIGIN_USAGE):
            # a device lane concretely reached this origin-guarded
            # branch; the banked witness carries the issue
            return []
        try:
            witness = solver.get_transaction_sequence(
                state, copy(state.world_state.constraints)
            )
        except UnsatError:
            return []
        # the JUMPI maps to the if/require in source
        return [
            Issue(
                swc_id=TX_ORIGIN_USAGE,
                title="Dependence on tx.origin",
                severity="Low",
                description_head=(
                    "Use of tx.origin as a part of authorization control."
                ),
                description_tail=REMEDIATION,
                gas_used=gas_range(state),
                transaction_sequence=witness,
                **found_at(state),
            )
        ]


detector = TxOrigin()
