"""SWC-124: write to an attacker-chosen storage slot.

Covers mythril/analysis/module/modules/arbitrary_write.py. Two-phase:
the cheap local property is "the written slot can equal an arbitrary
sentinel value"; full validation happens at transaction end.
"""

from __future__ import annotations

import logging

from mythril_tpu.analysis.module.dsl import (
    DeferredDetector,
    PotentialIssue,
    found_at,
)
from mythril_tpu.analysis.swc_data import WRITE_TO_ARBITRARY_STORAGE
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.smt import symbol_factory

log = logging.getLogger(__name__)

#: arbitrary sentinel: a slot pinned by the storage layout can't equal it
SENTINEL_SLOT = 324345425435

REMEDIATION = (
    "It is possible to write to arbitrary storage locations. By modifying the values of "
    "storage variables, attackers may bypass security controls or manipulate the business logic of "
    "the smart contract."
)


class ArbitraryStorage(DeferredDetector):
    """Searches for a feasible write to an arbitrary storage slot."""

    name = "Caller can write to arbitrary storage locations"
    swc_id = WRITE_TO_ARBITRARY_STORAGE
    description = "Search for any writes to an arbitrary storage slot"
    pre_hooks = ["SSTORE"]

    def _analyze_state(self, state: GlobalState) -> list:
        slot = state.mstate.stack[-1]
        reachable_with_sentinel = state.world_state.constraints + [
            slot == symbol_factory.BitVecVal(SENTINEL_SLOT, 256)
        ]
        return [
            PotentialIssue(
                swc_id=WRITE_TO_ARBITRARY_STORAGE,
                title="Write to an arbitrary storage location",
                severity="High",
                description_head=(
                    "The caller can write to arbitrary storage locations."
                ),
                description_tail=REMEDIATION,
                detector=self,
                constraints=reachable_with_sentinel,
                **found_at(state),
            )
        ]


detector = ArbitraryStorage()
