"""SWC-116/120: control flow depends on predictable block variables.

Covers mythril/analysis/module/modules/dependence_on_predictable_vars.py
— post-hooks on COINBASE/GASLIMIT/TIMESTAMP/NUMBER taint the pushed
symbol; BLOCKHASH of a potentially-old block taints too; the JUMPI
pre-hook reports branches on tainted values.
"""

from __future__ import annotations

import logging

from mythril_tpu.analysis import solver
from mythril_tpu.analysis.module.dsl import (
    ImmediateDetector,
    Issue,
    UnsatError,
    found_at,
    gas_range,
)
from mythril_tpu.analysis.module.module_helpers import is_prehook
from mythril_tpu.analysis.swc_data import TIMESTAMP_DEPENDENCE, WEAK_RANDOMNESS
from mythril_tpu.laser.ethereum.state.annotation import StateAnnotation
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.smt import ULT, symbol_factory

log = logging.getLogger(__name__)

PREDICTABLE_OPS = ["COINBASE", "GASLIMIT", "TIMESTAMP", "NUMBER"]

REMEDIATION = (
    "Note that the values of variables like coinbase, gaslimit, block number and timestamp "
    "are predictable and can be manipulated by a malicious miner. Also keep in mind that "
    "attackers know hashes of earlier blocks. Don't use any of those environment variables "
    "as sources of randomness and be aware that use of these variables introduces "
    "a certain level of trust into miners."
)


class PredictableValueAnnotation:
    """Symbol annotation: value derives from a predictable env var."""

    def __init__(self, operation: str) -> None:
        self.operation = operation


class OldBlockNumberUsedAnnotation(StateAnnotation):
    """State annotation: BLOCKHASH was queried for a prior block."""


class PredictableVariables(ImmediateDetector):
    """Detects control-flow decisions on predictable parameters."""

    name = "Control flow depends on a predictable environment variable"
    swc_id = "{} {}".format(TIMESTAMP_DEPENDENCE, WEAK_RANDOMNESS)
    description = (
        "Check whether control flow decisions are influenced by block.coinbase,"
        "block.gaslimit, block.timestamp or block.number."
    )
    pre_hooks = ["JUMPI", "BLOCKHASH"]
    post_hooks = ["BLOCKHASH"] + PREDICTABLE_OPS

    def _analyze_state(self, state: GlobalState) -> list:
        if is_prehook():
            opcode = state.get_current_instruction()["opcode"]
            if opcode == "JUMPI":
                return self._report_tainted_branch(state)
            # BLOCKHASH pre-hook: can the queried block be strictly
            # older than the current one? (upper bound on the block
            # number prevents overflow witnesses)
            height = state.mstate.stack[-1]
            in_the_past = [
                ULT(height, state.environment.block_number),
                ULT(
                    state.environment.block_number,
                    symbol_factory.BitVecVal(2**255, 256),
                ),
            ]
            try:
                solver.get_model(state.world_state.constraints + in_the_past)
                state.annotate(OldBlockNumberUsedAnnotation())
            except UnsatError:
                pass
            return []

        # post-hooks: taint the value the opcode just pushed
        produced_by = state.environment.code.instruction_list[
            state.mstate.pc - 1
        ]["opcode"]
        if produced_by == "BLOCKHASH":
            if any(state.get_annotations(OldBlockNumberUsedAnnotation)):
                state.mstate.stack[-1].annotate(
                    PredictableValueAnnotation(
                        "The block hash of a previous block"
                    )
                )
        else:
            state.mstate.stack[-1].annotate(
                PredictableValueAnnotation(
                    "The block.{} environment variable".format(
                        produced_by.lower()
                    )
                )
            )
        return []

    @staticmethod
    def _report_tainted_branch(state: GlobalState) -> list:
        findings = []
        from mythril_tpu.analysis.prepass import device_already_proved

        for taint in state.mstate.stack[-2].annotations:
            if not isinstance(taint, PredictableValueAnnotation):
                continue
            swc = (
                TIMESTAMP_DEPENDENCE
                if "timestamp" in taint.operation
                else WEAK_RANDOMNESS
            )
            if device_already_proved(state, swc):
                # a device lane concretely reached this branch; the
                # banked witness carries the issue
                continue
            try:
                witness = solver.get_transaction_sequence(
                    state, state.world_state.constraints
                )
            except UnsatError:
                continue
            findings.append(
                Issue(
                    swc_id=swc,
                    title="Dependence on predictable environment variable",
                    severity="Low",
                    description_head=(
                        "A control flow decision is made based on {}.".format(
                            taint.operation
                        )
                    ),
                    description_tail=(
                        taint.operation
                        + " is used to determine a control flow decision. "
                        + REMEDIATION
                    ),
                    gas_used=gas_range(state),
                    transaction_sequence=witness,
                    **found_at(state),
                )
            )
        return findings


detector = PredictableVariables()
