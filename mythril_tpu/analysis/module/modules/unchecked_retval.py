"""SWC-104: return value of an external call is never checked.

Reference parity: mythril/analysis/module/modules/unchecked_retval.py
:31-131 — CALL-family post-hooks collect retval symbols; at STOP/RETURN
a retval that can still be both 0 and 1 was never constrained.
"""

from __future__ import annotations

import logging
from copy import copy
from typing import List, Mapping, Union, cast

from mythril_tpu.analysis import solver
from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.swc_data import UNCHECKED_RET_VAL
from mythril_tpu.exceptions import UnsatError
from mythril_tpu.laser.ethereum.state.annotation import StateAnnotation
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.smt.bitvec import BitVec

log = logging.getLogger(__name__)


class UncheckedRetvalAnnotation(StateAnnotation):
    def __init__(self) -> None:
        self.retvals: List[Mapping[str, Union[int, BitVec]]] = []

    def __copy__(self):
        result = UncheckedRetvalAnnotation()
        result.retvals = copy(self.retvals)
        return result


class UncheckedRetval(DetectionModule):
    """Tests whether CALL return values are checked."""

    name = "Return value of an external call is not checked"
    swc_id = UNCHECKED_RET_VAL
    description = (
        "Test whether CALL return value is checked. "
        "For direct calls, the Solidity compiler auto-generates this check."
        " For low-level-calls the check is omitted."
    )
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["STOP", "RETURN"]
    post_hooks = ["CALL", "DELEGATECALL", "STATICCALL", "CALLCODE"]

    def _execute(self, state: GlobalState) -> None:
        if state.get_current_instruction()["address"] in self.cache:
            return
        issues = self._analyze_state(state)
        for issue in issues:
            self.cache.add(issue.address)
        self.issues.extend(issues)

    def _analyze_state(self, state: GlobalState) -> list:
        instruction = state.get_current_instruction()

        annotations = cast(
            List[UncheckedRetvalAnnotation],
            [a for a in state.get_annotations(UncheckedRetvalAnnotation)],
        )
        if len(annotations) == 0:
            state.annotate(UncheckedRetvalAnnotation())
            annotations = cast(
                List[UncheckedRetvalAnnotation],
                [a for a in state.get_annotations(UncheckedRetvalAnnotation)],
            )
        retvals = annotations[0].retvals

        if instruction["opcode"] in ("STOP", "RETURN"):
            issues = []
            for retval in retvals:
                try:
                    # unconstrained = both outcomes still satisfiable
                    solver.get_transaction_sequence(
                        state, state.world_state.constraints + [retval["retval"] == 1]
                    )
                    transaction_sequence = solver.get_transaction_sequence(
                        state, state.world_state.constraints + [retval["retval"] == 0]
                    )
                except UnsatError:
                    continue

                description_tail = (
                    "External calls return a boolean value. If the callee halts with an exception, 'false' is "
                    "returned and execution continues in the caller. "
                    "The caller should check whether an exception happened and react accordingly to avoid unexpected "
                    "behavior. For example it is often desirable to wrap external calls in require() so the "
                    "transaction is reverted if the call fails."
                )
                issues.append(
                    Issue(
                        contract=state.environment.active_account.contract_name,
                        function_name=state.environment.active_function_name,
                        address=retval["address"],
                        bytecode=state.environment.code.bytecode,
                        title="Unchecked return value from external call.",
                        swc_id=UNCHECKED_RET_VAL,
                        severity="Medium",
                        description_head="The return value of a message call is not checked.",
                        description_tail=description_tail,
                        gas_used=(
                            state.mstate.min_gas_used,
                            state.mstate.max_gas_used,
                        ),
                        transaction_sequence=transaction_sequence,
                    )
                )
            return issues

        log.debug("End of call, extracting retval")
        assert state.environment.code.instruction_list[state.mstate.pc - 1][
            "opcode"
        ] in ["CALL", "DELEGATECALL", "STATICCALL", "CALLCODE"]
        return_value = state.mstate.stack[-1]
        retvals.append(
            {"address": state.instruction["address"] - 1, "retval": return_value}
        )
        return []


detector = UncheckedRetval()
