"""SWC-104: return value of an external call is never checked.

Covers mythril/analysis/module/modules/unchecked_retval.py —
CALL-family post-hooks collect retval symbols; a retval that can still
be both 0 and 1 when the transaction ends was never constrained.
"""

from __future__ import annotations

import logging
from copy import copy
from typing import List, Mapping, Union

from mythril_tpu.analysis import solver
from mythril_tpu.analysis.module.dsl import (
    ImmediateDetector,
    Issue,
    UnsatError,
    found_at,
    gas_range,
)
from mythril_tpu.analysis.swc_data import UNCHECKED_RET_VAL
from mythril_tpu.laser.ethereum.state.annotation import StateAnnotation
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.smt.bitvec import BitVec

log = logging.getLogger(__name__)

CALL_OPS = ("CALL", "DELEGATECALL", "STATICCALL", "CALLCODE")

REMEDIATION = (
    "External calls return a boolean value. If the callee halts with an exception, 'false' is "
    "returned and execution continues in the caller. "
    "The caller should check whether an exception happened and react accordingly to avoid unexpected "
    "behavior. For example it is often desirable to wrap external calls in require() so the "
    "transaction is reverted if the call fails."
)


class UncheckedRetvalAnnotation(StateAnnotation):
    def __init__(self) -> None:
        self.retvals: List[Mapping[str, Union[int, BitVec]]] = []

    def __copy__(self):
        twin = UncheckedRetvalAnnotation()
        twin.retvals = copy(self.retvals)
        return twin


def _retval_log(state: GlobalState) -> list:
    tracker = next(iter(state.get_annotations(UncheckedRetvalAnnotation)), None)
    if tracker is None:
        tracker = UncheckedRetvalAnnotation()
        state.annotate(tracker)
    return tracker.retvals


class UncheckedRetval(ImmediateDetector):
    """Tests whether CALL return values are checked."""

    name = "Return value of an external call is not checked"
    swc_id = UNCHECKED_RET_VAL
    description = (
        "Test whether CALL return value is checked. "
        "For direct calls, the Solidity compiler auto-generates this check."
        " For low-level-calls the check is omitted."
    )
    pre_hooks = ["STOP", "RETURN"]
    post_hooks = list(CALL_OPS)

    def _analyze_state(self, state: GlobalState) -> list:
        instruction = state.get_current_instruction()
        pending = _retval_log(state)

        if instruction["opcode"] not in ("STOP", "RETURN"):
            # CALL-family post-hook: remember the pushed retval symbol
            log.debug("End of call, extracting retval")
            prev_op = state.environment.code.instruction_list[
                state.mstate.pc - 1
            ]["opcode"]
            assert prev_op in CALL_OPS
            pending.append(
                {
                    "address": state.instruction["address"] - 1,
                    "retval": state.mstate.stack[-1],
                }
            )
            return []

        from mythril_tpu.analysis.prepass import device_already_proved

        found = []
        for entry in pending:
            if device_already_proved(
                state, UNCHECKED_RET_VAL, address=entry["address"]
            ):
                # a device lane ran this call and halted with no branch
                # after it — the banked witness carries the issue
                continue
            try:
                # unconstrained = both outcomes still satisfiable
                solver.get_transaction_sequence(
                    state,
                    state.world_state.constraints + [entry["retval"] == 1],
                )
                witness = solver.get_transaction_sequence(
                    state,
                    state.world_state.constraints + [entry["retval"] == 0],
                )
            except UnsatError:
                continue
            found.append(
                Issue(
                    title="Unchecked return value from external call.",
                    swc_id=UNCHECKED_RET_VAL,
                    severity="Medium",
                    description_head=(
                        "The return value of a message call is not checked."
                    ),
                    description_tail=REMEDIATION,
                    gas_used=gas_range(state),
                    transaction_sequence=witness,
                    **found_at(state, address=entry["address"]),
                )
            )
        return found


detector = UncheckedRetval()
