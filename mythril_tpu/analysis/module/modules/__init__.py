"""The built-in detection modules (reference:
mythril/analysis/module/modules/)."""
