"""SWC-110: reachable assert violation (INVALID/0xfe).

Covers mythril/analysis/module/modules/exceptions.py.
"""

from __future__ import annotations

import logging

from mythril_tpu.analysis.module.dsl import (
    ImmediateDetector,
    Issue,
    UnsatError,
    found_at,
    gas_range,
)
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import ASSERT_VIOLATION
from mythril_tpu.laser.ethereum.state.global_state import GlobalState

log = logging.getLogger(__name__)

REMEDIATION = (
    "It is possible to trigger an assertion violation. Note that Solidity assert() statements should "
    "only be used to check invariants. Review the transaction trace generated for this issue and "
    "either make sure your program logic is correct, or use require() instead of assert() if your goal "
    "is to constrain user inputs or enforce preconditions. Remember to validate inputs from both callers "
    "(for instance, via passed arguments) and callees (for instance, via return values)."
)


class Exceptions(ImmediateDetector):
    """Checks whether any exception state (ASSERT_FAIL) is reachable."""

    name = "Assertion violation"
    swc_id = ASSERT_VIOLATION
    description = "Checks whether any exception states are reachable."
    pre_hooks = ["ASSERT_FAIL"]

    def _analyze_state(self, state: GlobalState) -> list:
        log.debug(
            "ASSERT_FAIL in function %s",
            state.environment.active_function_name,
        )
        from mythril_tpu.analysis.prepass import device_already_proved

        if device_already_proved(state, ASSERT_VIOLATION):
            # the device prepass banked a concrete witness here; its
            # issue merges in at fire_lasers — skip the Optimize query
            return []
        try:
            witness = get_transaction_sequence(
                state, state.world_state.constraints
            )
        except UnsatError:
            log.debug("no model found")
            return []
        return [
            Issue(
                swc_id=ASSERT_VIOLATION,
                title="Exception State",
                severity="Medium",
                description_head="An assertion violation was triggered.",
                description_tail=REMEDIATION,
                transaction_sequence=witness,
                gas_used=gas_range(state),
                **found_at(state),
            )
        ]


detector = Exceptions()
