"""Helpers for detection modules (reference:
mythril/analysis/module/module_helpers.py)."""

import traceback


def is_prehook() -> bool:
    """True when called from inside the engine's pre-hook dispatch.

    Same stack-inspection trick as the reference, made robust to call
    depth by scanning the recent frames instead of one fixed offset
    (the post-hook dispatcher's name contains "post_hook", never
    "pre_hook", so the scan cannot misfire).
    """
    return any("pre_hook" in frame for frame in traceback.format_stack()[-6:])
