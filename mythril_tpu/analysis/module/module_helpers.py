"""Helpers for detection modules (reference:
mythril/analysis/module/module_helpers.py)."""

import traceback


def is_prehook() -> bool:
    """True when called from inside the engine's pre-hook dispatch.

    The reference inspects the Python stack for its dispatcher's
    function name; this engine's hook bus records the phase explicitly
    (hooks.py `_PHASE`), which cannot misfire with frame depth or
    renamed dispatchers. The stack scan survives only as a fallback
    for direct calls outside any dispatch (unit tests driving
    _analyze_state by hand)."""
    from mythril_tpu.laser.ethereum.hooks import current_hook_phase

    phase = current_hook_phase()
    if phase is not None:
        return phase == "pre"
    return any("pre_hook" in frame for frame in traceback.format_stack()[-6:])
