"""DetectionModule interface.

Reference parity: mythril/analysis/module/base.py:29-94 — modules
declare name/swc_id/description, an entry point (CALLBACK modules hook
opcodes; POST modules scan the finished statespace), and pre/post hook
opcode lists; `execute(target)` is the engine-facing entry.
"""

from __future__ import annotations

import logging
from abc import ABC, abstractmethod
from enum import Enum
from typing import List, Optional, Set

from mythril_tpu.analysis.report import Issue
from mythril_tpu.laser.ethereum.state.global_state import GlobalState

log = logging.getLogger(__name__)


class EntryPoint(Enum):
    """POST modules scan the statespace after execution; CALLBACK
    modules run from opcode hooks during execution (preferred — POST
    slows the analysis down)."""

    POST = 1
    CALLBACK = 2


class DetectionModule(ABC):
    """Base class for every detection rule."""

    name = "Detection Module Name / Title"
    swc_id = "SWC-000"
    description = "Detection module description"
    entry_point: EntryPoint = EntryPoint.CALLBACK
    pre_hooks: List[str] = []
    post_hooks: List[str] = []

    def __init__(self) -> None:
        self.issues: List[Issue] = []
        self.cache: Set[int] = set()

    def reset_module(self):
        # also drop the dedupe cache: it scopes one analysis, and a
        # long-lived process (corpus mode, tests) would otherwise
        # suppress identical addresses across unrelated contracts
        # (the reference only clears `issues`, which leaks exactly that
        # way when its API is driven in-process)
        self.issues = []
        self.cache = set()

    def execute(self, target: GlobalState) -> Optional[List[Issue]]:
        from mythril_tpu.observe.querylog import query_context

        log.debug("Entering analysis module: %s", self.__class__.__name__)
        # solver queries issued inside a module carry the "module"
        # origin in the query flight recorder (observe/querylog.py)
        with query_context("module"):
            result = self._execute(target)
        log.debug("Exiting analysis module: %s", self.__class__.__name__)
        return result

    @abstractmethod
    def _execute(self, target) -> Optional[List[Issue]]:
        """Module main method (override this)."""

    def __repr__(self) -> str:
        return (
            "<DetectionModule name={0.name} swc_id={0.swc_id} "
            "pre_hooks={0.pre_hooks} post_hooks={0.post_hooks} "
            "description={0.description}>"
        ).format(self)
