"""Device evidence -> concrete Issues, for every detection class the
corpus exercises (round 5: the device owns detection, the host
verifies).

The explorer's evidence bank (laser/batch/explore.py
`_consume_evidence`) records only CONCRETELY exhibited facts — a lane
that actually wrapped and used the result, actually sent a
gas-forwarding call to the attacker, actually decided a branch on
tx.origin — each with the replayable calldata that did it. Synthesis
here is therefore solver-free: the banked input IS the transaction
sequence, exactly like the assert/selfdestruct witnesses in
analysis/prepass.py.

Fingerprint parity: every Issue matches the corresponding host
module's (address, swc, title) so the report dedupe collapses the two
paths and `device_already_proved` can stand in for the module's
expensive solve:

- wrap events        -> SWC-101  analysis/module/modules/integer.py
- unchecked calls    -> SWC-104  unchecked_retval.py
- value to attacker  -> SWC-105  ether_thief.py
- call to attacker   -> SWC-107  external_calls.py
- state after call   -> SWC-107  state_change_external_calls.py
- delegatecall       -> SWC-112  delegatecall.py
- origin branches    -> SWC-115  dependence_on_origin.py
- predictable-var branches -> SWC-116/120 dependence_on_predictable_vars.py

Reference anchor for the flow being short-circuited:
mythril/analysis/solver.py:47-242 (get_transaction_sequence) invoked
per candidate site by each of the modules above.
"""

from __future__ import annotations

import logging
from typing import Dict, List

from mythril_tpu.analysis.module.modules import (
    delegatecall as _delegatecall_mod,
    ether_thief as _ether_mod,
    external_calls as _external_mod,
    integer as _integer_mod,
    unchecked_retval as _retval_mod,
    dependence_on_origin as _origin_mod,
    dependence_on_predictable_vars as _predictable_mod,
    state_change_external_calls as _state_change_mod,
)
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.swc_data import (
    DELEGATECALL_TO_UNTRUSTED_CONTRACT,
    INTEGER_OVERFLOW_AND_UNDERFLOW,
    REENTRANCY,
    TX_ORIGIN_USAGE,
    UNCHECKED_RET_VAL,
    UNPROTECTED_ETHER_WITHDRAWAL,
)

log = logging.getLogger(__name__)

GAS_STIPEND = 2300


def _mk_issue(
    contract, runtime_hex: str, address: int, rec: Dict, **fields
) -> Issue:
    from mythril_tpu.analysis.prepass import (
        _function_name,
        _witness_sequence,
    )

    calldata = bytes.fromhex(rec["input"])
    prefix = [bytes.fromhex(p) for p in rec.get("prefix", [])]
    issue = Issue(
        contract=contract.name,
        function_name=_function_name(contract, calldata),
        address=rec["pc"],
        bytecode=runtime_hex,
        gas_used=(rec.get("gas_min"), rec.get("gas_max")),
        transaction_sequence=_witness_sequence(
            address,
            prefix + [calldata],
            runtime_hex,
            initial_storage=rec.get("initial_storage"),
            values=(
                list(rec.get("prefix_values") or [])
                + [rec.get("call_value", 0)]
            ),
            initial_balance=rec.get("initial_balance", 0),
        ),
        **fields,
    )
    issue.provenance = "device-evidence"
    return issue


def _call_issues(contract, runtime_hex, address, rec) -> List[Issue]:
    out = []
    if rec.get("unchecked"):
        # per-property witness: the lane that PROVED the property
        # (explore.py banks w_unchecked/w_profit beside the shared
        # record), so the reported transaction_sequence replays the
        # claim even when another lane owns the record's main witness
        out.append(
            _mk_issue(
                contract,
                runtime_hex,
                address,
                {**rec, **rec.get("w_unchecked", {})},
                swc_id=UNCHECKED_RET_VAL,
                title="Unchecked return value from external call.",
                severity="Medium",
                description_head=(
                    "The return value of a message call is not checked."
                ),
                description_tail=_retval_mod.REMEDIATION,
            )
        )
    if rec.get("value_to_attacker"):
        out.append(
            _mk_issue(
                contract,
                runtime_hex,
                address,
                {**rec, **rec.get("w_profit", {})},
                swc_id=UNPROTECTED_ETHER_WITHDRAWAL,
                title="Unprotected Ether Withdrawal",
                severity="High",
                description_head=(
                    "Any sender can withdraw Ether from the contract account."
                ),
                description_tail=_ether_mod.REMEDIATION,
            )
        )
    if rec.get("to_attacker") and rec.get("attacker_gas", rec.get("gas", 0)) > GAS_STIPEND:
        if rec["kind"] == "CALL":
            out.append(
                _mk_issue(
                    contract,
                    runtime_hex,
                    address,
                    rec,
                    swc_id=REENTRANCY,
                    title="External Call To User-Supplied Address",
                    severity="Low",
                    description_head=(
                        "A call to a user-supplied address is executed."
                    ),
                    description_tail=_external_mod.REMEDIATION,
                )
            )
        elif rec["kind"] == "DELEGATECALL":
            out.append(
                _mk_issue(
                    contract,
                    runtime_hex,
                    address,
                    rec,
                    swc_id=DELEGATECALL_TO_UNTRUSTED_CONTRACT,
                    title="Delegatecall to user-supplied address",
                    severity="High",
                    description_head=(
                        "The contract delegates execution to another "
                        "contract with a user-supplied address."
                    ),
                    description_tail=_delegatecall_mod.REMEDIATION,
                )
            )
    return out


def evidence_issues(contract, outcome: Dict, address: int) -> List[Issue]:
    """Concrete Issues from the prepass outcome's evidence records."""
    from mythril_tpu.analysis.prepass import REPLAY_GAS_LIMIT

    records = (outcome or {}).get("evidence") or []
    runtime_hex = getattr(contract, "code", "") or ""
    if runtime_hex.startswith("0x"):
        runtime_hex = runtime_hex[2:]

    # state-access severity mirrors the reference's user-defined-vs-
    # fixed callee split: any attacker-targetable call in this contract
    # upgrades the reentrancy surface to Medium
    user_defined_callee = any(
        rec.get("to_attacker") or rec.get("target_tainted")
        for rec in records
        if rec.get("class") == "call"
    )

    issues: List[Issue] = []
    for rec in records:
        if (rec.get("gas_min") or 0) > REPLAY_GAS_LIMIT:
            continue  # the claimed replay gas limit could not reach it
        cls = rec.get("class")
        if cls == "wrap":
            issues.append(
                _mk_issue(
                    contract,
                    runtime_hex,
                    address,
                    rec,
                    swc_id=INTEGER_OVERFLOW_AND_UNDERFLOW,
                    title="Integer Arithmetic Bugs",
                    severity="High",
                    description_head="The arithmetic operator can {}.".format(
                        "underflow"
                        if rec["op"] == "subtraction"
                        else "overflow"
                    ),
                    description_tail=_integer_mod.REMEDIATION,
                )
            )
        elif cls == "call":
            issues.extend(_call_issues(contract, runtime_hex, address, rec))
        elif cls == "state_acc":
            access_kind = "Read of" if rec["access"] == "SLOAD" else "Write to"
            address_kind = "user defined" if user_defined_callee else "fixed"
            issues.append(
                _mk_issue(
                    contract,
                    runtime_hex,
                    address,
                    rec,
                    swc_id=REENTRANCY,
                    title="State access after external call",
                    severity="Medium" if user_defined_callee else "Low",
                    description_head=(
                        f"{access_kind} persistent state following "
                        "external call"
                    ),
                    description_tail=(
                        _state_change_mod.DESCRIPTION_TAIL_TEMPLATE.format(
                            address_kind
                        )
                    ),
                )
            )
        elif cls == "env":
            if rec["swc"] == TX_ORIGIN_USAGE:
                issues.append(
                    _mk_issue(
                        contract,
                        runtime_hex,
                        address,
                        rec,
                        swc_id=TX_ORIGIN_USAGE,
                        title="Dependence on tx.origin",
                        severity="Low",
                        description_head=(
                            "Use of tx.origin as a part of authorization "
                            "control."
                        ),
                        description_tail=_origin_mod.REMEDIATION,
                    )
                )
            else:
                operation = rec.get("operation") or ""
                issues.append(
                    _mk_issue(
                        contract,
                        runtime_hex,
                        address,
                        rec,
                        swc_id=rec["swc"],
                        title="Dependence on predictable environment variable",
                        severity="Low",
                        description_head=(
                            "A control flow decision is made based on "
                            "{}.".format(operation)
                        ),
                        description_tail=(
                            operation
                            + " is used to determine a control flow "
                            "decision. " + _predictable_mod.REMEDIATION
                        ),
                    )
                )
    if issues:
        log.info(
            "Device evidence synthesized %d issue(s) across %s",
            len(issues),
            sorted({i.swc_id for i in issues}),
        )
    return issues
