"""Device-prepass witnesses -> concrete Issues.

The device symbolic explorer (laser/batch/explore.py) banks the halt
pc + the concrete calldata of every lane that died on an ASSERT_FAIL.
Those witnesses ARE proofs: replaying the banked calldata from a fresh
state reaches the faulting instruction deterministically, so the
analysis layer emits the issue directly — witness as the transaction
sequence — instead of having the host engine re-derive the same assert
through a solver walk.

Reference anchors: the issue flow this short-circuits is
mythril/analysis/solver.py:47-242 (`get_transaction_sequence`) feeding
mythril/analysis/module/modules/exceptions.py (SWC-110). The issue
text matches the host Exceptions module so the Report fingerprint
(contract+address+title) dedups the two paths cleanly.
"""

from __future__ import annotations

import logging
from typing import Dict, List

from mythril_tpu.analysis.module.modules.exceptions import REMEDIATION
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.swc_data import (
    ASSERT_VIOLATION,
    UNPROTECTED_SELFDESTRUCT,
)
from mythril_tpu.laser.ethereum.transaction.symbolic import ACTORS

log = logging.getLogger(__name__)

ASSERT_FAIL_BYTE = 0xFE

#: (runtime_hex, address, swc_id) triples the device already holds a
#: concrete witness for: the host detection modules skip their own
#: witness-concretization solve there and let the banked device issue
#: carry the finding (reset per analysis by SymExecWrapper). Keyed by
#: bytecode so creation-code pcs and dynloaded foreign contracts never
#: collide with the analyzed runtime's pc space.
_PROVEN: set = set()


def _norm_code(code_hex: str) -> str:
    code_hex = code_hex or ""
    return code_hex[2:] if code_hex.startswith("0x") else code_hex


def reset_proven() -> None:
    _PROVEN.clear()


def register_proven(issues, code_hex: str) -> None:
    code_hex = _norm_code(code_hex)
    for issue in issues:
        _PROVEN.add((code_hex, issue.address, issue.swc_id))


def device_already_proved(state, swc_id: str, address: int = None) -> bool:
    """True when the prepass banked a concrete witness for the code
    this state is executing, at `address` (default: the current
    instruction) — the module's Optimize query would re-derive what a
    concrete execution already established."""
    if not _PROVEN:
        return False
    code_hex = _norm_code(getattr(state.environment.code, "bytecode", ""))
    if address is None:
        address = state.get_current_instruction()["address"]
    key = (code_hex, address, swc_id)
    if key in _PROVEN:
        from mythril_tpu.laser.smt.solver.solver_statistics import (
            SolverStatistics,
        )

        SolverStatistics().device_cert_count += 1
        return True
    return False

#: the gas limit the jsonv2 replay context claims (report.py
#: REPLAY_BLOCK_CONTEXT gasLimit); witnesses that need more gas than
#: this would not replay, so they are not reported
REPLAY_GAS_LIMIT = 0x7D000


def _function_name(contract, calldata: bytes) -> str:
    """Resolve the witness's entry function from its selector."""
    if len(calldata) < 4:
        return "fallback"
    selector = "0x" + calldata[:4].hex()
    disassembly = getattr(contract, "disassembly", None)
    table = getattr(disassembly, "function_hash_to_name", None) or {}
    if selector in table:
        return table[selector]
    if selector in getattr(disassembly, "func_hashes", []):
        return "_function_" + selector
    return "fallback"


def _witness_sequence(
    contract_address: int,
    transactions: List[bytes],
    runtime_hex: str,
    initial_storage: Dict = None,
    values: List[int] = None,
    initial_balance: int = 0,
) -> Dict:
    """A replayable transaction sequence in the shape
    `get_transaction_sequence` produces (analysis/solver.py): one step
    per attacker transaction, the last one the triggering call.
    `initial_storage` declares a poisoned-carry witness's synthetic
    start state (the concolic form of the reference's symbolic initial
    storage) so the claim is honest about what it assumes."""
    import json

    attacker = "0x" + ("%x" % ACTORS.attacker.value).zfill(40)
    target = hex(contract_address)
    return {
        "initialState": {
            "accounts": {
                target: {
                    "nonce": 0,
                    "code": runtime_hex,
                    "storage": (
                        json.dumps(initial_storage, sort_keys=True)
                        if initial_storage
                        else "{}"
                    ),
                    "balance": hex(initial_balance or 0),
                },
                attacker: {
                    "nonce": 0,
                    "code": "",
                    "storage": "{}",
                    "balance": "0x0",
                },
            }
        },
        "steps": [
            {
                "input": "0x" + step.hex(),
                "value": (
                    hex(values[i]) if values and i < len(values) else "0x0"
                ),
                "origin": attacker,
                "address": target,
                "calldata": "0x" + step.hex(),
            }
            for i, step in enumerate(transactions)
        ],
    }


KILL_REMEDIATION = (
    "Any sender can trigger execution of the SELFDESTRUCT instruction to "
    "destroy this contract account. Review the transaction trace generated "
    "for this issue and make sure that appropriate security controls are in "
    "place to prevent unrestricted access."
)


def _issue_from_record(
    contract, record: Dict, address: int, runtime_hex: str, kind: str
) -> Issue:
    calldata = bytes.fromhex(record["input"])
    prefix = [bytes.fromhex(p) for p in record.get("prefix", [])]
    if kind == "selfdestruct":
        swc_id, title, severity = (
            UNPROTECTED_SELFDESTRUCT,
            "Unprotected Selfdestruct",
            "High",
        )
        head = "Any sender can cause the contract to self-destruct."
        tail = KILL_REMEDIATION
    else:
        swc_id, title, severity = ASSERT_VIOLATION, "Exception State", "Medium"
        head = "An assertion violation was triggered."
        tail = REMEDIATION
    issue = Issue(
        contract=contract.name,
        function_name=_function_name(contract, calldata),
        address=record["pc"],
        swc_id=swc_id,
        title=title,
        bytecode=runtime_hex,
        gas_used=(record.get("gas_min"), record.get("gas_max")),
        severity=severity,
        description_head=head,
        description_tail=tail,
        transaction_sequence=_witness_sequence(
            address,
            prefix + [calldata],
            runtime_hex,
            initial_storage=record.get("initial_storage"),
            values=(
                list(record.get("prefix_values") or [])
                + [record.get("call_value", 0)]
            ),
            initial_balance=record.get("initial_balance", 0),
        ),
    )
    issue.provenance = "device-prepass"
    return issue


def witness_issues(contract, outcome: Dict, address: int) -> List[Issue]:
    """Concrete Issues carried by the prepass outcome's trigger bank.

    - assert-violation lanes whose faulting byte is the designated
      INVALID opcode (0xfe) -> SWC-110 "Exception State". Lanes that
      died on merely-undefined opcodes are execution errors, not
      assertions, exactly as in the host engine's ASSERT_FAIL hook.
    - selfdestruct lanes -> SWC-106 "Unprotected Selfdestruct": the
      lane IS an attacker-sent call chain that executed SELFDESTRUCT.
    """
    triggers = (outcome or {}).get("triggers") or {}
    runtime_hex = getattr(contract, "code", "") or ""
    if runtime_hex.startswith("0x"):
        runtime_hex = runtime_hex[2:]
    code = bytes.fromhex(runtime_hex)

    issues: List[Issue] = []
    for kind in ("assert-violation", "selfdestruct"):
        for record in triggers.get(kind) or []:
            pc = record["pc"]
            if kind == "assert-violation" and not (
                0 <= pc < len(code) and code[pc] == ASSERT_FAIL_BYTE
            ):
                continue
            if (record.get("gas_min") or 0) > REPLAY_GAS_LIMIT:
                continue  # the claimed replay gas limit could not reach it
            issue = _issue_from_record(contract, record, address, runtime_hex, kind)
            issues.append(issue)
            log.info(
                "Device prepass witnessed SWC-%s at pc %d (%s)",
                issue.swc_id,
                pc,
                issue.function,
            )
    # the round-5 evidence classes (wraps, calls, env branches) ride
    # the same outcome; synthesis lives in analysis/evidence.py
    try:
        from mythril_tpu.analysis.evidence import evidence_issues

        issues.extend(evidence_issues(contract, outcome, address))
    except Exception:
        log.debug("evidence synthesis failed", exc_info=True)
    return issues
