"""Device-prepass witnesses -> concrete Issues.

The device symbolic explorer (laser/batch/explore.py) banks the halt
pc + the concrete calldata of every lane that died on an ASSERT_FAIL.
Those witnesses ARE proofs: replaying the banked calldata from a fresh
state reaches the faulting instruction deterministically, so the
analysis layer emits the issue directly — witness as the transaction
sequence — instead of having the host engine re-derive the same assert
through a solver walk.

Reference anchors: the issue flow this short-circuits is
mythril/analysis/solver.py:47-242 (`get_transaction_sequence`) feeding
mythril/analysis/module/modules/exceptions.py (SWC-110). The issue
text matches the host Exceptions module so the Report fingerprint
(contract+address+title) dedups the two paths cleanly.
"""

from __future__ import annotations

import logging
from typing import Dict, List

from mythril_tpu.analysis.module.modules.exceptions import REMEDIATION
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.swc_data import ASSERT_VIOLATION
from mythril_tpu.laser.ethereum.transaction.symbolic import ACTORS

log = logging.getLogger(__name__)

ASSERT_FAIL_BYTE = 0xFE

#: the gas limit the jsonv2 replay context claims (report.py
#: REPLAY_BLOCK_CONTEXT gasLimit); witnesses that need more gas than
#: this would not replay, so they are not reported
REPLAY_GAS_LIMIT = 0x7D000


def _function_name(contract, calldata: bytes) -> str:
    """Resolve the witness's entry function from its selector."""
    if len(calldata) < 4:
        return "fallback"
    selector = "0x" + calldata[:4].hex()
    disassembly = getattr(contract, "disassembly", None)
    table = getattr(disassembly, "function_hash_to_name", None) or {}
    if selector in table:
        return table[selector]
    if selector in getattr(disassembly, "func_hashes", []):
        return "_function_" + selector
    return "fallback"


def _witness_sequence(contract_address: int, calldata: bytes, runtime_hex: str) -> Dict:
    """A replayable single-transaction sequence in the shape
    `get_transaction_sequence` produces (analysis/solver.py)."""
    attacker = "0x" + ("%x" % ACTORS.attacker.value).zfill(40)
    target = hex(contract_address)
    data_hex = "0x" + calldata.hex()
    return {
        "initialState": {
            "accounts": {
                target: {
                    "nonce": 0,
                    "code": runtime_hex,
                    "storage": "{}",
                    "balance": "0x0",
                },
                attacker: {
                    "nonce": 0,
                    "code": "",
                    "storage": "{}",
                    "balance": "0x0",
                },
            }
        },
        "steps": [
            {
                "input": data_hex,
                "value": "0x0",
                "origin": attacker,
                "address": target,
                "calldata": data_hex,
            }
        ],
    }


def witness_issues(contract, outcome: Dict, address: int) -> List[Issue]:
    """Concrete Issues carried by the prepass outcome's trigger bank.

    Currently: assert-violation lanes whose faulting byte is the
    designated INVALID opcode (0xfe) -> SWC-110 "Exception State".
    Lanes that died on merely-undefined opcodes are execution errors,
    not assertions, exactly as in the host engine's ASSERT_FAIL hook.
    """
    triggers = (outcome or {}).get("triggers") or {}
    witnesses = triggers.get("assert-violation") or []
    if not witnesses:
        return []

    runtime_hex = getattr(contract, "code", "") or ""
    if runtime_hex.startswith("0x"):
        runtime_hex = runtime_hex[2:]
    code = bytes.fromhex(runtime_hex)

    issues: List[Issue] = []
    for record in witnesses:
        pc = record["pc"]
        if not (0 <= pc < len(code)) or code[pc] != ASSERT_FAIL_BYTE:
            continue
        if (record.get("gas_min") or 0) > REPLAY_GAS_LIMIT:
            continue  # the claimed replay gas limit could not reach it
        calldata = bytes.fromhex(record["input"])
        issue = Issue(
            contract=contract.name,
            function_name=_function_name(contract, calldata),
            address=pc,
            swc_id=ASSERT_VIOLATION,
            title="Exception State",
            bytecode=runtime_hex,
            gas_used=(record.get("gas_min"), record.get("gas_max")),
            severity="Medium",
            description_head="An assertion violation was triggered.",
            description_tail=REMEDIATION,
            transaction_sequence=_witness_sequence(address, calldata, runtime_hex),
        )
        issue.provenance = "device-prepass"
        issues.append(issue)
        log.info(
            "Device prepass witnessed SWC-110 at pc %d (%s)", pc, issue.function
        )
    return issues
