"""Two-phase issue flow.

Covers mythril/analysis/potential_issues.py. Detection modules
pre-solve only their cheap local property and park a `PotentialIssue`
on the state; when the engine finishes a transaction it calls
`check_potential_issues`, which solves the full path + property
constraints and, on sat, concretizes the exploit transactions and
promotes the finding onto its detector.
"""

from __future__ import annotations

from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.exceptions import UnsatError
from mythril_tpu.laser.ethereum.state.annotation import StateAnnotation
from mythril_tpu.laser.ethereum.state.global_state import GlobalState

#: the fields a PotentialIssue shares verbatim with the Issue it becomes
_CARRIED_FIELDS = (
    "title",
    "contract",
    "function_name",
    "address",
    "description_head",
    "description_tail",
    "severity",
    "swc_id",
    "bytecode",
)


class PotentialIssue:
    """A finding whose cheap precondition was satisfiable; full
    validation is deferred to transaction end."""

    def __init__(self, detector, constraints=None, **fields):
        self.detector = detector
        self.constraints = constraints or []
        for name in _CARRIED_FIELDS:
            setattr(self, name, fields.pop(name, "" if "descr" in name else None))
        if fields:
            raise TypeError(f"unknown PotentialIssue fields: {sorted(fields)}")

    def promote(self, state: GlobalState, transaction_sequence) -> Issue:
        """The finished Issue, with gas bounds and the concrete
        witness filled in from the validating state."""
        return Issue(
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            transaction_sequence=transaction_sequence,
            **{name: getattr(self, name) for name in _CARRIED_FIELDS},
        )


class PotentialIssuesAnnotation(StateAnnotation):
    def __init__(self):
        self.potential_issues = []


def get_potential_issues_annotation(state: GlobalState) -> PotentialIssuesAnnotation:
    """The state's potential-issues annotation (created on demand)."""
    existing = next(iter(state.get_annotations(PotentialIssuesAnnotation)), None)
    if existing is not None:
        return existing
    fresh = PotentialIssuesAnnotation()
    state.annotate(fresh)
    return fresh


def check_potential_issues(state: GlobalState) -> None:
    """Validate every pending potential issue against the full path
    constraints; sat findings move onto their detectors as Issues.

    Candidates the device prepass already holds a concrete witness for
    (same code, address, and SWC class) skip the expensive validation
    solve — the banked device issue carries the finding with an
    identical fingerprint (analysis/evidence.py)."""
    from mythril_tpu.analysis.prepass import device_already_proved

    pending = get_potential_issues_annotation(state)
    for candidate in pending.potential_issues[:]:
        if device_already_proved(
            state, candidate.swc_id, address=candidate.address
        ):
            pending.potential_issues.remove(candidate)
            candidate.detector.cache.add(candidate.address)
            continue
        try:
            witness = get_transaction_sequence(
                state, state.world_state.constraints + candidate.constraints
            )
        except UnsatError:
            continue
        pending.potential_issues.remove(candidate)
        candidate.detector.cache.add(candidate.address)
        candidate.detector.issues.append(candidate.promote(state, witness))
