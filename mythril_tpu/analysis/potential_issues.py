"""Two-phase issue flow.

Reference parity: mythril/analysis/potential_issues.py:8-108 —
detection modules pre-solve only their cheap local property and attach
a `PotentialIssue` to the state; at transaction end
`check_potential_issues` (called from the engine) solves the full
path + property constraints and, on sat, builds the concrete
transaction sequence and promotes the potential issue to a real one.
"""

from __future__ import annotations

from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.exceptions import UnsatError
from mythril_tpu.laser.ethereum.state.annotation import StateAnnotation
from mythril_tpu.laser.ethereum.state.global_state import GlobalState


class PotentialIssue:
    """An issue whose cheap precondition was satisfiable; final
    validation is deferred to transaction end."""

    def __init__(
        self,
        contract,
        function_name,
        address,
        swc_id,
        title,
        bytecode,
        detector,
        severity=None,
        description_head="",
        description_tail="",
        constraints=None,
    ):
        self.title = title
        self.contract = contract
        self.function_name = function_name
        self.address = address
        self.description_head = description_head
        self.description_tail = description_tail
        self.severity = severity
        self.swc_id = swc_id
        self.bytecode = bytecode
        self.constraints = constraints or []
        self.detector = detector


class PotentialIssuesAnnotation(StateAnnotation):
    def __init__(self):
        self.potential_issues = []


def get_potential_issues_annotation(state: GlobalState) -> PotentialIssuesAnnotation:
    """The state's potential-issues annotation (created on demand)."""
    for annotation in state.annotations:
        if isinstance(annotation, PotentialIssuesAnnotation):
            return annotation
    annotation = PotentialIssuesAnnotation()
    state.annotate(annotation)
    return annotation


def check_potential_issues(state: GlobalState) -> None:
    """Validate each pending potential issue against the full path
    constraints; sat -> concrete tx sequence -> Issue on the detector."""
    annotation = get_potential_issues_annotation(state)
    for potential_issue in annotation.potential_issues[:]:
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints + potential_issue.constraints
            )
        except UnsatError:
            continue

        annotation.potential_issues.remove(potential_issue)
        potential_issue.detector.cache.add(potential_issue.address)
        potential_issue.detector.issues.append(
            Issue(
                contract=potential_issue.contract,
                function_name=potential_issue.function_name,
                address=potential_issue.address,
                title=potential_issue.title,
                bytecode=potential_issue.bytecode,
                swc_id=potential_issue.swc_id,
                gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
                severity=potential_issue.severity,
                description_head=potential_issue.description_head,
                description_tail=potential_issue.description_tail,
                transaction_sequence=transaction_sequence,
            )
        )
