"""Value-set facts extracted from the taint fixpoint.

Where `taint.py` answers "who influences this sink", this pass
answers "what concrete values can it hold": the constant half of the
sink records is distilled into

- **resolved call targets** — CALL/CALLCODE/DELEGATECALL/STATICCALL
  sites whose callee address is a provable constant. These are the
  cross-contract facts ROADMAP item 4 needs: a corpus scheduler can
  pre-load a constant callee's code into the arena before the wave
  that calls it.
- **constant storage slots** — SSTORE/SLOAD sites with constant
  slots, split into read/written sets. A contract whose entire
  storage footprint is constant slots is the easy case for
  incremental re-analysis (item 3): a diff touching none of them
  cannot invalidate banked storage facts.
- **assertion-marker evidence** — the two concrete triggers the
  `UserAssertions` detector keys on: the AssertionFailed(string) LOG1
  topic and the MythX `0xcafecafe…` MSTORE marker word. The topic is
  checked against constant LOG1 topics from the taint pass; the
  marker is a byte scan over the raw code (a PUSHed marker always
  appears in the code bytes; the scan over-approximates into
  non-PUSH positions, which only ever mounts more).

The constants duplicate two values from
`analysis/module/modules/user_assertions.py` and
`laser/ethereum/transaction/symbolic.py` so `myth lint` keeps its
no-jax/no-smt import budget (same pattern as the engine's local
trigger-kind table).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from mythril_tpu.analysis.static.taint import TaintResult

#: user_assertions.ASSERTION_FAILED_TOPIC — emit AssertionFailed(string)
ASSERTION_FAILED_TOPIC = (
    0xB42604CB105A16C8F6DB8A41E6B00C0C1B4826465E8BC504B3EB3E88B3E6A4A0
)
#: user_assertions.MSTORE_MARKER, as the hex byte pattern the code
#: scan looks for (30 bytes: "cafe" fifteen times)
MSTORE_MARKER_HEX = "cafe" * 15

#: transaction.symbolic._ATTACKER_DEFAULT — the actor address the
#: delegatecall/external-call properties pin the target to
ATTACKER_ADDRESS = 0xDEADBEEFDEADBEEFDEADBEEFDEADBEEFDEADBEEF

#: don't let a pathological contract bloat stats()/lint_dict()
_EXPORT_CAP = 64


class ValueSets:
    """The distilled constant facts for one bytecode."""

    def __init__(self) -> None:
        #: pc -> constant callee address (all call kinds)
        self.resolved_call_targets: Dict[int, int] = {}
        #: pc -> call kind for the resolved targets
        self.call_kinds: Dict[int, str] = {}
        self.constant_storage_writes: Set[int] = set()
        self.constant_storage_reads: Set[int] = set()
        #: code bytes contain the MythX assertion marker word
        self.marker_in_code = False
        #: a constant LOG1 topic equals the AssertionFailed topic
        self.assert_topic_logged = False

    def stats(self) -> Dict:
        slots = sorted(
            self.constant_storage_writes | self.constant_storage_reads
        )
        return {
            "resolved_call_targets": {
                str(pc): hex(target)
                for pc, target in sorted(
                    self.resolved_call_targets.items()
                )[:_EXPORT_CAP]
            },
            "resolved_call_target_count": len(self.resolved_call_targets),
            "constant_storage_slots": [
                hex(s) for s in slots[:_EXPORT_CAP]
            ],
            "constant_storage_slot_count": len(slots),
        }


def value_sets(
    taint: Optional[TaintResult], code: bytes
) -> ValueSets:
    """Post-process the taint fixpoint's sink constants (+ the raw
    code scan). A missing/incomplete taint result yields only the
    byte-scan facts — still sound, just empty-handed."""
    out = ValueSets()
    out.marker_in_code = MSTORE_MARKER_HEX in code.hex()
    if taint is None or taint.incomplete:
        return out
    for pc, site in taint.call_sites.items():
        target = site["target"][0]
        if target is not None:
            out.resolved_call_targets[pc] = target
            out.call_kinds[pc] = site["kind"]
    for pc, slot in taint.sstore_slots.items():
        if slot[0] is not None:
            out.constant_storage_writes.add(slot[0])
    for pc, slot in taint.sload_slots.items():
        if slot[0] is not None:
            out.constant_storage_reads.add(slot[0])
    out.assert_topic_logged = any(
        topic[0] == ASSERTION_FAILED_TOPIC
        for topic in taint.log1_topics.values()
        if topic[0] is not None
    )
    return out


def assertion_evidence(
    taint: Optional[TaintResult], vsa: ValueSets
) -> bool:
    """Can the UserAssertions detector possibly fire? Either LOG1
    evidence (a topic that is — or might be — the AssertionFailed
    topic) or the MSTORE marker word somewhere in the code. With no
    usable taint result the caller must fall back to the opcode
    screen instead of consulting this."""
    if vsa.marker_in_code or vsa.assert_topic_logged:
        return True
    if taint is None or taint.incomplete:
        return True  # no flow facts: keep the module
    return any(
        topic[0] is None for topic in taint.log1_topics.values()
    )
