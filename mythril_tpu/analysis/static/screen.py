"""Detector pre-screen: per-module opcode/feature signatures.

Each detection module can only ever fire if certain opcodes exist in
the analyzed code (a module that reports unchecked CALL return values
is inert on a contract with no CALL-family opcode). The signature is a
conjunction of disjunctions over opcode names: the module applies iff
EVERY group has at least one member present in the feature set.

The feature set is the opcode names of the (conservatively) reachable
instructions — an unresolved computed jump makes every JUMPDEST block
reachable, and on any dataflow bail the whole instruction stream
counts — so screening a module out is sound: no execution of this
code can reach an opcode the screen says is absent.

Skipping a module buys two things per contract: its opcode hooks are
never mounted (the svm's hook dispatch runs per executed instruction)
and its POST pass never scans the statespace.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Set, Tuple

CALL_FAMILY = ("CALL", "CALLCODE", "DELEGATECALL", "STATICCALL")

#: module class name -> conjunction of opcode-name disjunctions.
#: A module absent from this table is never screened (always loaded).
MODULE_SIGNATURES: Dict[str, Tuple[Tuple[str, ...], ...]] = {
    # jump-target hijack needs a jump
    "ArbitraryJump": (("JUMP", "JUMPI"),),
    # arbitrary storage write needs a store
    "ArbitraryStorage": (("SSTORE",),),
    "ArbitraryDelegateCall": (("DELEGATECALL",),),
    "TxOrigin": (("ORIGIN",),),
    "PredictableVariables": (
        ("BLOCKHASH", "COINBASE", "GASLIMIT", "TIMESTAMP", "NUMBER"),
    ),
    # its post hooks (ether_thief.py)
    "EtherThief": (("CALL", "STATICCALL"),),
    "Exceptions": (("ASSERT_FAIL",),),
    "ExternalCalls": (("CALL",),),
    "IntegerArithmetics": (("ADD", "SUB", "MUL", "EXP"),),
    "MultipleSends": (CALL_FAMILY,),
    # needs an external call AND a state access after it
    "StateChangeAfterCall": (
        ("CALL", "DELEGATECALL", "CALLCODE"),
        ("SSTORE", "SLOAD", "CREATE", "CREATE2"),
    ),
    "AccidentallyKillable": (("SUICIDE",),),
    "UncheckedRetval": (CALL_FAMILY,),
    # solc assertion markers ride LOG1 (event) or MSTORE (panic word);
    # MSTORE is near-ubiquitous, so this screen rarely fires — kept
    # for raw runtime bodies that touch no memory at all
    "UserAssertions": (("LOG1", "MSTORE"),),
}


def module_applicable(module_name: str, features: Set[str]) -> bool:
    signature = MODULE_SIGNATURES.get(module_name)
    if signature is None:
        return True
    return all(any(op in features for op in group) for group in signature)


def screen_modules(
    features: Iterable[str],
    module_names: Iterable[str] = None,
) -> Tuple[List[str], List[str]]:
    """(applicable, skipped) module class names for a feature set.

    `module_names` defaults to every registered detection module."""
    feature_set = set(features)
    if module_names is None:
        from mythril_tpu.analysis.module import ModuleLoader

        module_names = [
            type(module).__name__
            for module in ModuleLoader().get_detection_modules()
        ]
    applicable, skipped = [], []
    for name in module_names:
        (applicable if module_applicable(name, feature_set) else skipped).append(
            name
        )
    return applicable, skipped
