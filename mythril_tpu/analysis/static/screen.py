"""Detector pre-screen: opcode signatures + semantic sink predicates.

Two layers, applied in order:

1. **Opcode signatures** — each detection module can only ever fire
   if certain opcodes exist in the analyzed code (a module that
   reports unchecked CALL return values is inert on a contract with
   no CALL-family opcode). The signature is a conjunction of
   disjunctions over opcode names: the module applies iff EVERY group
   has at least one member present in the feature set. The feature
   set is the opcode names of the (conservatively) reachable
   instructions — an unresolved computed jump makes every JUMPDEST
   block reachable, and on any dataflow bail the whole instruction
   stream counts.
2. **Sink predicates** (`SINK_PREDICATES`) — for modules whose opcode
   is near-ubiquitous the signature screens almost nothing, so a
   second test runs over the taint/value-set fixpoint (taint.py /
   vsa.py): the module mounts only if its *sink* can actually carry
   the property it detects — a JUMP whose target might be symbolic,
   an SSTORE whose slot is not a provable constant, a CALL that can
   move value, an ORIGIN that reaches a branch guard. Each predicate
   mirrors the UNSAT-pruning its module performs symbolically (the
   module bodies in analysis/module/modules/ are the ground truth;
   every predicate cites the constraint it pre-evaluates). On any
   taint bail (`incomplete`) the predicate layer is skipped entirely
   and the opcode screen alone decides — the conservative fallback.

Both layers only ever err toward mounting: screening a module out is
sound — no execution of this code can make that module fire. Pinned
by the screen-soundness sweep over every module's positive fixture
(tests/analysis/test_static_taint.py).

Skipping a module buys two things per contract: its opcode hooks are
never mounted (the svm's hook dispatch runs per executed instruction)
and its POST pass never scans the statespace. When EVERY module
screens off, the static-answer triage tier (summary.py
`static_answerable`) settles the whole contract without touching the
device.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from mythril_tpu.analysis.static.taint import TaintResult
from mythril_tpu.analysis.static.vsa import (
    ATTACKER_ADDRESS,
    ValueSets,
    assertion_evidence,
)

CALL_FAMILY = ("CALL", "CALLCODE", "DELEGATECALL", "STATICCALL")

#: module class name -> conjunction of opcode-name disjunctions.
#: A module absent from this table is never screened (always loaded).
MODULE_SIGNATURES: Dict[str, Tuple[Tuple[str, ...], ...]] = {
    # jump-target hijack needs a jump
    "ArbitraryJump": (("JUMP", "JUMPI"),),
    # arbitrary storage write needs a store
    "ArbitraryStorage": (("SSTORE",),),
    "ArbitraryDelegateCall": (("DELEGATECALL",),),
    "TxOrigin": (("ORIGIN",),),
    "PredictableVariables": (
        ("BLOCKHASH", "COINBASE", "GASLIMIT", "TIMESTAMP", "NUMBER"),
    ),
    # its post hooks (ether_thief.py)
    "EtherThief": (("CALL", "STATICCALL"),),
    "Exceptions": (("ASSERT_FAIL",),),
    "ExternalCalls": (("CALL",),),
    "IntegerArithmetics": (("ADD", "SUB", "MUL", "EXP"),),
    "MultipleSends": (CALL_FAMILY,),
    # needs an external call AND a state access after it
    "StateChangeAfterCall": (
        ("CALL", "DELEGATECALL", "CALLCODE"),
        ("SSTORE", "SLOAD", "CREATE", "CREATE2"),
    ),
    "AccidentallyKillable": (("SUICIDE",),),
    "UncheckedRetval": (CALL_FAMILY,),
    # solc assertion markers ride LOG1 (event) or MSTORE (panic word);
    # MSTORE is near-ubiquitous so this layer alone screens almost
    # nothing — the real screen is the semantic predicate below
    # (LOG1-topic / marker-word evidence)
    "UserAssertions": (("LOG1", "MSTORE"),),
}

# ---------------------------------------------------------------------------
# the semantic layer: per-module sink predicates
# ---------------------------------------------------------------------------
#: arbitrary_write.SENTINEL_SLOT — a constant slot can only satisfy
#: `slot == sentinel` if it IS the sentinel
_SENTINEL_SLOT = 324345425435
#: external_calls pins UGT(gas, 2300)
_GAS_STIPEND = 2300


def _nonconst(value) -> bool:
    return value is None or value[0] is None


def _sink_arbitrary_jump(t: TaintResult, v: ValueSets) -> bool:
    # arbitrary_jump fires iff stack[-1].symbolic at JUMP/JUMPI; a
    # provable constant is never symbolic
    return any(_nonconst(val) for val in t.jump_targets.values())


def _sink_arbitrary_storage(t: TaintResult, v: ValueSets) -> bool:
    # arbitrary_write adds `slot == SENTINEL_SLOT`: UNSAT for every
    # constant slot that is not the sentinel itself
    return any(
        _nonconst(slot) or slot[0] == _SENTINEL_SLOT
        for slot in t.sstore_slots.values()
    )


def _sink_delegatecall(t: TaintResult, v: ValueSets) -> bool:
    # delegatecall pins `target == ACTORS.attacker`
    return any(
        site["kind"] == "DELEGATECALL"
        and (
            _nonconst(site["target"])
            or site["target"][0] == ATTACKER_ADDRESS
        )
        for site in t.call_sites.values()
    )


def _sink_ether_thief(t: TaintResult, v: ValueSets) -> bool:
    # ether_thief needs the attacker's balance to GROW before its
    # CALL/STATICCALL post-hook observes it: a CALL moving nonzero
    # value does that (STATICCALL never carries value; a constant-zero
    # value moves nothing) — and so does SELFDESTRUCT in an earlier
    # transaction (vm/flow.py credits the heir's balance), so any
    # reachable SUICIDE keeps the module too
    if t.selfdestruct_sites:
        return True
    return any(
        site["kind"] == "CALL"
        and (_nonconst(site["value"]) or site["value"][0] > 0)
        for site in t.call_sites.values()
    )


def _sink_external_calls(t: TaintResult, v: ValueSets) -> bool:
    # external_calls pins `target == attacker AND UGT(gas, 2300)`
    return any(
        site["kind"] == "CALL"
        and (
            _nonconst(site["target"])
            or site["target"][0] == ATTACKER_ADDRESS
        )
        and (_nonconst(site["gas"]) or site["gas"][0] > _GAS_STIPEND)
        for site in t.call_sites.values()
    )


def _sink_integer(t: TaintResult, v: ValueSets) -> bool:
    # integer.py annotates ADD/SUB/MUL/EXP whose wrap condition is
    # satisfiable: all-constant, non-wrapping operands never are
    return bool(t.arith_unsafe_pcs)


def _sink_tx_origin(t: TaintResult, v: ValueSets) -> bool:
    # dependence_on_origin fires iff an ORIGIN-derived value reaches a
    # JUMPI guard — exactly the ORIGIN-provenance condition fact
    return bool(t.origin_condition_pcs)


def _sink_user_assertions(t: TaintResult, v: ValueSets) -> bool:
    # the satellite fix for the self-admitted dead MSTORE screen:
    # user_assertions fires on the AssertionFailed LOG1 topic or a
    # CONCRETE MSTORE of the MythX marker word (symbolic stores raise
    # LookupError in the module) — LOG1-topic / marker-scan evidence
    return assertion_evidence(t, v)


#: module class name -> predicate over (TaintResult, ValueSets);
#: True = the sink can carry the property, the module must mount.
#: A module absent here is decided by its opcode signature alone.
SINK_PREDICATES: Dict[
    str, Callable[[TaintResult, ValueSets], bool]
] = {
    "ArbitraryJump": _sink_arbitrary_jump,
    "ArbitraryStorage": _sink_arbitrary_storage,
    "ArbitraryDelegateCall": _sink_delegatecall,
    "EtherThief": _sink_ether_thief,
    "ExternalCalls": _sink_external_calls,
    "IntegerArithmetics": _sink_integer,
    "TxOrigin": _sink_tx_origin,
    "UserAssertions": _sink_user_assertions,
}


def module_applicable(
    module_name: str,
    features: Set[str],
    taint: Optional[TaintResult] = None,
    vsa: Optional[ValueSets] = None,
) -> bool:
    signature = MODULE_SIGNATURES.get(module_name)
    if signature is None:
        return True
    if not all(
        any(op in features for op in group) for group in signature
    ):
        return False
    if taint is None or taint.incomplete or vsa is None:
        return True  # conservative fallback: opcode screen decides
    predicate = SINK_PREDICATES.get(module_name)
    if predicate is None:
        return True
    return predicate(taint, vsa)


def screen_modules(
    features: Iterable[str],
    module_names: Iterable[str] = None,
    taint: Optional[TaintResult] = None,
    vsa: Optional[ValueSets] = None,
) -> Tuple[List[str], List[str]]:
    """(applicable, skipped) module class names for a feature set.

    With `taint`/`vsa` (a completed taint fixpoint + its value sets)
    the semantic sink predicates refine the opcode screen; without
    them — or on an incomplete fixpoint — the opcode layer alone
    decides. `module_names` defaults to every registered detection
    module."""
    feature_set = set(features)
    if module_names is None:
        from mythril_tpu.analysis.module import ModuleLoader

        module_names = [
            type(module).__name__
            for module in ModuleLoader().get_detection_modules()
        ]
    applicable, skipped = [], []
    for name in module_names:
        (
            applicable
            if module_applicable(name, feature_set, taint=taint, vsa=vsa)
            else skipped
        ).append(name)
    return applicable, skipped
