"""Attacker-taint dataflow over the recovered CFG.

A worklist fixpoint (same shape as `dataflow.py`, which must have run
first — jump resolution and dead directions are reused, not
recomputed) propagating an attacker-influence lattice per abstract
stack slot. The abstract value is ``(const, taint)``:

- ``const`` is the constant-lattice half ({int} < TOP=None), folded
  with the same `_fold` the dataflow pass uses;
- ``taint`` is a provenance bitmask: ATTACKER (CALLDATALOAD/COPY,
  CALLER, CALLVALUE, returndata), ORIGIN and CALLER provenance bits
  (kept separately so "tx.origin guards a branch" is a distinct
  fact), and UNKNOWN (storage/balance/env/memory — symbolic in
  execution, but not attacker-steered).

Indirect flows join conservatively:

- **memory** is one accumulated taint mask (`mem_taint`): any tainted
  MSTORE/CALLDATACOPY/call-return-write taints every later MLOAD/SHA3
  — the "MLOAD after tainted MSTORE" join;
- **storage** keeps a per-constant-slot written-taint map plus an
  any-slot mask for writes at unknown slots; SLOAD joins the slot's
  written taint with UNKNOWN (initial storage is symbolic);
- values that leave the modeled stack window (depth cap, join
  truncation, under-window SWAP) fold their taint into a sticky
  per-state *spill* mask that every under-window pop returns with —
  provenance is never silently dropped.

The recording pass (final states only, like dataflow's) lands one
fact per **sink** instruction: JUMP/JUMPI target and condition,
CALL-family target/value/gas, SSTORE slot+value, SLOAD slot,
SELFDESTRUCT beneficiary, LOG1 topic, ORIGIN/CALLER reaching a
comparison or branch guard, and ADD/SUB/MUL/EXP sites whose operands
are not provably constant (or whose constants wrap). `screen.py`
layers the per-module sink predicates on these facts; `summary.py`
turns the ATTACKER-bit sinks into `myth lint` findings.

Soundness direction: every approximation here makes values LESS
constant and MORE tainted, so a sink the result calls clean is clean
on every real execution — the invariant the semantic detector screen
and the static-answer triage tier stand on. Any bail (visit cap,
upstream dataflow incompleteness, an exception) sets `incomplete` and
every consumer falls back to the opcode screen.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Set, Tuple

from mythril_tpu.analysis.static.cfg import CFG, BasicBlock, stack_effect
from mythril_tpu.analysis.static.dataflow import (
    _BINARY,
    _fold,
    DataflowResult,
    DEPTH_CAP,
    MASK,
    WORD,
)

log = logging.getLogger(__name__)

# -- the provenance lattice --------------------------------------------------
TAINT_ATTACKER = 1  #: calldata / caller / callvalue / returndata
TAINT_ORIGIN = 2  #: derived from ORIGIN
TAINT_CALLER = 4  #: derived from CALLER (auth-check evidence)
TAINT_UNKNOWN = 8  #: symbolic but not attacker-steered (storage, env)
#: a value whose provenance was lost could be anything
TAINT_ANY = TAINT_ATTACKER | TAINT_ORIGIN | TAINT_CALLER | TAINT_UNKNOWN

#: abstract value: (constant int | None, taint mask)
AbsVal = Tuple[Optional[int], int]

CLEAN_UNKNOWN: AbsVal = (None, TAINT_UNKNOWN)

#: taint worklist backstop (the pass reruns the fixpoint while the
#: memory/storage accumulators grow, so the cap is total visits)
TAINT_VISIT_CAP = 120_000
#: outer accumulator rounds (masks are monotone 4-bit; this never
#: triggers on sane code — pure paranoia against a dict-growth loop)
ACCUM_ROUNDS_CAP = 8

_COMPARISONS = frozenset(["EQ", "LT", "GT", "SLT", "SGT"])
_ARITH_SINKS = frozenset(["ADD", "SUB", "MUL", "EXP"])
#: writes into memory whose payload the attacker steers
_MEM_ATTACKER_WRITES = frozenset(["CALLDATACOPY", "RETURNDATACOPY"])
_CALL_ARITY = {"CALL": 7, "CALLCODE": 7, "DELEGATECALL": 6, "STATICCALL": 6}
#: CALL/CALLCODE carry a value operand; DELEGATECALL/STATICCALL do not
_CALL_HAS_VALUE = ("CALL", "CALLCODE")

_SOURCE_PUSH = {
    # opcode -> taint of the pushed value (all 0-pop pushes)
    "CALLDATASIZE": TAINT_ATTACKER,
    "CALLVALUE": TAINT_ATTACKER,
    "RETURNDATASIZE": TAINT_ATTACKER,
    "CALLER": TAINT_ATTACKER | TAINT_CALLER,
    "ORIGIN": TAINT_ATTACKER | TAINT_ORIGIN,
    "TIMESTAMP": TAINT_UNKNOWN,
    "NUMBER": TAINT_UNKNOWN,
    "COINBASE": TAINT_UNKNOWN,
    "DIFFICULTY": TAINT_UNKNOWN,
    "PREVRANDAO": TAINT_UNKNOWN,
    "GASLIMIT": TAINT_UNKNOWN,
    "GASPRICE": TAINT_UNKNOWN,
    "CHAINID": TAINT_UNKNOWN,
    "BASEFEE": TAINT_UNKNOWN,
    "SELFBALANCE": TAINT_UNKNOWN,
    "GAS": TAINT_UNKNOWN,
    "MSIZE": TAINT_UNKNOWN,
    "ADDRESS": TAINT_UNKNOWN,
    "CODESIZE": TAINT_UNKNOWN,
}


class TaintState:
    """Abstract state at a block boundary: the top-window of abstract
    values plus the spill mask for everything below the window."""

    __slots__ = ("stack", "spill")

    def __init__(self, stack: Tuple[AbsVal, ...], spill: int) -> None:
        self.stack = stack
        self.spill = spill

    def key(self) -> Tuple:
        return (self.stack, self.spill)

    @staticmethod
    def empty() -> "TaintState":
        return TaintState((), 0)

    @staticmethod
    def unknown() -> "TaintState":
        # broadcast entry: nothing on the model stack, everything
        # below it could be anything
        return TaintState((), TAINT_ANY)


def join(a: Optional[TaintState], b: TaintState) -> TaintState:
    if a is None:
        return b
    n = min(len(a.stack), len(b.stack))
    spill = a.spill | b.spill
    # entries a join truncates fold their taint into the spill mask
    for dropped in a.stack[: len(a.stack) - n]:
        spill |= dropped[1]
    for dropped in b.stack[: len(b.stack) - n]:
        spill |= dropped[1]
    if n:
        merged = tuple(
            (x[0] if x[0] == y[0] else None, x[1] | y[1])
            for x, y in zip(a.stack[-n:], b.stack[-n:])
        )
    else:
        merged = ()
    return TaintState(merged, spill)


class TaintResult:
    """Per-sink facts at the fixpoint (consumed by screen/summary)."""

    def __init__(self) -> None:
        self.incomplete = False
        self.reachable: Set[int] = set()
        #: sink operands, keyed by instruction address
        self.jump_targets: Dict[int, AbsVal] = {}
        self.jumpi_conditions: Dict[int, AbsVal] = {}
        self.sstore_slots: Dict[int, AbsVal] = {}
        self.sstore_values: Dict[int, AbsVal] = {}
        self.sload_slots: Dict[int, AbsVal] = {}
        #: pc -> {"kind", "target", "value" (CALL/CALLCODE), "gas"}
        self.call_sites: Dict[int, Dict] = {}
        self.selfdestruct_sites: Dict[int, AbsVal] = {}
        self.log1_topics: Dict[int, AbsVal] = {}
        #: JUMPI guards carrying ORIGIN / CALLER provenance
        self.origin_condition_pcs: List[int] = []
        self.caller_condition_pcs: List[int] = []
        #: EQ/LT/GT/SLT/SGT with an ORIGIN-derived operand
        self.origin_compare_pcs: List[int] = []
        #: ADD/SUB/MUL/EXP whose operands are not provably constant,
        #: or whose constant fold wraps — the sites symbolic execution
        #: could annotate as overflowing
        self.arith_unsafe_pcs: Set[int] = set()
        self.mem_taint = 0
        self.storage_written: Dict[int, int] = {}
        self.storage_any_taint = 0
        self.wall_ms = 0.0

    # -- derived views ---------------------------------------------------
    def sink_counts(self) -> Dict[str, int]:
        """Per-sink-kind totals (routing features / stats)."""
        return {
            "jump_target": len(self.jump_targets),
            "jumpi_condition": len(self.jumpi_conditions),
            "sstore_slot": len(self.sstore_slots),
            "call_target": len(self.call_sites),
            "selfdestruct": len(self.selfdestruct_sites),
            "log1_topic": len(self.log1_topics),
            "origin_condition": len(self.origin_condition_pcs),
            "arith_unsafe": len(self.arith_unsafe_pcs),
        }

    def tainted_sink_counts(self) -> Dict[str, int]:
        """Per-sink-kind counts carrying the ATTACKER bit."""

        def _n(table: Dict[int, AbsVal]) -> int:
            return sum(
                1 for v in table.values() if v[1] & TAINT_ATTACKER
            )

        return {
            "jump_target": _n(self.jump_targets),
            "jumpi_condition": _n(self.jumpi_conditions),
            "sstore_slot": _n(self.sstore_slots),
            "call_target": sum(
                1
                for site in self.call_sites.values()
                if site["target"][1] & TAINT_ATTACKER
            ),
            "selfdestruct": _n(self.selfdestruct_sites),
            "log1_topic": _n(self.log1_topics),
        }

    @property
    def taint_density(self) -> float:
        """Tainted sinks / total sinks — the routing-feature scalar."""
        total = sum(self.sink_counts().values())
        tainted = sum(self.tainted_sink_counts().values()) + len(
            self.origin_condition_pcs
        ) + len(self.arith_unsafe_pcs)
        return round(min(1.0, tainted / total), 4) if total else 0.0

    def tainted_call_sites(self, kind: Optional[str] = None) -> List[int]:
        """pcs of CALL-family sites whose target carries ATTACKER."""
        return sorted(
            pc
            for pc, site in self.call_sites.items()
            if (kind is None or site["kind"] == kind)
            and site["target"][1] & TAINT_ATTACKER
        )

    def tainted_jump_pcs(self) -> List[int]:
        return sorted(
            pc
            for pc, v in self.jump_targets.items()
            if v[1] & TAINT_ATTACKER
        )


class _Accumulators:
    """The flow-insensitive joins (memory / storage): monotone masks
    shared by every path, re-fixpointed until they stop growing."""

    __slots__ = ("mem", "storage", "storage_any", "dirty")

    def __init__(self) -> None:
        self.mem = 0
        self.storage: Dict[int, int] = {}
        self.storage_any = 0
        self.dirty = False

    def write_mem(self, taint: int) -> None:
        if taint & ~self.mem:
            self.mem |= taint
            self.dirty = True

    def write_storage(self, slot: Optional[int], taint: int) -> None:
        if slot is None:
            if taint & ~self.storage_any:
                self.storage_any |= taint
                self.dirty = True
            return
        have = self.storage.get(slot, 0)
        if taint & ~have:
            self.storage[slot] = have | taint
            self.dirty = True

    def read_storage(self, slot: Optional[int]) -> int:
        base = self.storage_any | TAINT_UNKNOWN
        if slot is None:
            out = base
            for taint in self.storage.values():
                out |= taint
            return out
        return base | self.storage.get(slot, 0)


def _wraps(op: str, a: int, b: int) -> bool:
    """Does the CONSTANT operation wrap mod 2**256? (a is stack top.)"""
    if op == "ADD":
        return a + b >= WORD
    if op == "SUB":
        return a - b < 0
    if op == "MUL":
        return a * b >= WORD
    if op == "EXP":
        try:
            return b > 1 and b ** a >= WORD
        except OverflowError:  # astronomically large exponent
            return True
    return False


def transfer(
    block: BasicBlock,
    state: TaintState,
    acc: _Accumulators,
    result: Optional[TaintResult] = None,
) -> TaintState:
    """One abstract pass over `block` from `state`. With `result`
    (the recording pass, fixpoint states only) sink facts land."""
    stack: List[AbsVal] = list(state.stack)
    spill = state.spill

    def pop() -> AbsVal:
        nonlocal spill
        if stack:
            return stack.pop()
        # below the modeled window: the value is whatever was spilled
        return (None, spill)

    def push(value: AbsVal) -> None:
        nonlocal spill
        stack.append(value)
        if len(stack) > DEPTH_CAP:
            spill |= stack[0][1]
            del stack[0]

    for ins in block.instructions:
        op = ins.opcode
        pc = ins.address
        if op.startswith("PUSH"):
            push((int(ins.argument, 16) if ins.argument else 0, 0))
        elif op.startswith("DUP"):
            n = int(op[3:])
            push(stack[-n] if len(stack) >= n else (None, spill))
        elif op.startswith("SWAP"):
            n = int(op[4:])
            if len(stack) >= n + 1:
                stack[-1], stack[-n - 1] = stack[-n - 1], stack[-1]
            elif stack:
                # the partner slot is below the window: the top sinks
                # into the spill, an unknown spilled value surfaces
                spill |= stack[-1][1]
                stack[-1] = (None, spill)
        elif op == "POP":
            pop()
        elif op in _BINARY:
            a, b = pop(), pop()
            const = _fold(op, a[0], b[0])
            taint = a[1] | b[1]
            if result is not None:
                if op in _ARITH_SINKS and (
                    a[0] is None
                    or b[0] is None
                    or _wraps(op, a[0], b[0])
                ):
                    result.arith_unsafe_pcs.add(pc)
                if op in _COMPARISONS and (
                    (a[1] | b[1]) & TAINT_ORIGIN
                ):
                    result.origin_compare_pcs.append(pc)
            push((const, taint))
        elif op == "ISZERO":
            a = pop()
            push((None if a[0] is None else int(a[0] == 0), a[1]))
        elif op == "NOT":
            a = pop()
            push((None if a[0] is None else (~a[0]) & MASK, a[1]))
        elif op == "CALLDATALOAD":
            pop()
            push((None, TAINT_ATTACKER))
        elif op in _SOURCE_PUSH:
            push((None, _SOURCE_PUSH[op]))
        elif op == "PC":
            push((pc, 0))
        elif op in _MEM_ATTACKER_WRITES:
            for _ in range(3):
                pop()
            acc.write_mem(TAINT_ATTACKER)
        elif op in ("MSTORE", "MSTORE8"):
            pop()  # offset
            value = pop()
            acc.write_mem(value[1])
        elif op == "MLOAD":
            pop()
            push((None, acc.mem))
        elif op == "SHA3":
            pop(), pop()
            push((None, acc.mem | TAINT_UNKNOWN))
        elif op == "SSTORE":
            slot = pop()
            value = pop()
            acc.write_storage(slot[0], value[1])
            if result is not None:
                result.sstore_slots[pc] = slot
                result.sstore_values[pc] = value
        elif op == "SLOAD":
            slot = pop()
            if result is not None:
                result.sload_slots[pc] = slot
            push((None, slot[1] | acc.read_storage(slot[0])))
        elif op == "JUMP":
            target = pop()
            if result is not None:
                result.jump_targets[pc] = target
        elif op == "JUMPI":
            target = pop()
            cond = pop()
            if result is not None:
                result.jump_targets[pc] = target
                result.jumpi_conditions[pc] = cond
                if cond[1] & TAINT_ORIGIN:
                    result.origin_condition_pcs.append(pc)
                if cond[1] & TAINT_CALLER:
                    result.caller_condition_pcs.append(pc)
        elif op in _CALL_ARITY:
            gas = pop()
            target = pop()
            value = pop() if op in _CALL_HAS_VALUE else None
            for _ in range(4):  # inoff, insz, outoff, outsz
                pop()
            # the callee writes the return area; with a non-constant
            # or attacker target the payload is attacker-chosen
            acc.write_mem(TAINT_ATTACKER | TAINT_UNKNOWN)
            if result is not None:
                result.call_sites[pc] = {
                    "kind": op,
                    "target": target,
                    "value": value,
                    "gas": gas,
                }
            push((None, TAINT_UNKNOWN))
        elif op == "SUICIDE":
            beneficiary = pop()
            if result is not None:
                result.selfdestruct_sites[pc] = beneficiary
        elif op == "LOG1":
            pop(), pop()  # offset, size
            topic = pop()
            if result is not None:
                result.log1_topics[pc] = topic
        elif op in ("BALANCE", "EXTCODESIZE", "EXTCODEHASH", "BLOCKHASH"):
            pop()
            push((None, TAINT_UNKNOWN))
        elif op in ("CREATE", "CREATE2"):
            pops, _ = stack_effect(op)
            for _ in range(pops):
                pop()
            acc.write_mem(TAINT_UNKNOWN)
            push((None, TAINT_UNKNOWN))
        else:
            # generic fallback: the output derives from the inputs
            # plus whatever the opcode reads that we do not model
            pops, pushes = stack_effect(op)
            taint = TAINT_UNKNOWN
            for _ in range(pops):
                taint |= pop()[1]
            for _ in range(pushes):
                push((None, taint))
    return TaintState(tuple(stack), spill)


def _successors(
    cfg: CFG, flow: DataflowResult, block: BasicBlock
) -> Tuple[List[int], bool]:
    """(successor starts, broadcast?) from the DATAFLOW fixpoint's
    jump facts — the two passes must agree on the graph they walk."""
    out: List[int] = []
    terminator = block.terminator
    if block.start in flow.underflow_blocks:
        return out, False
    if terminator in ("JUMP", "JUMPI"):
        pc = block.end
        broadcast = pc in flow.unresolved_jumps
        target = flow.resolved_jumps.get(pc)
        dead = {d for p, d in flow.dead_directions if p == pc}
        if target is not None and not (
            terminator == "JUMPI" and True in dead
        ):
            out.append(target)
        if terminator == "JUMPI" and False not in dead:
            nxt = cfg.block_after(block.start)
            if nxt is not None:
                out.append(nxt.start)
        return out, broadcast
    if terminator == "FALL":
        nxt = cfg.block_after(block.start)
        if nxt is not None:
            out.append(nxt.start)
    return out, False


def run_taint(cfg: CFG, flow: DataflowResult) -> TaintResult:
    """Worklist fixpoint + recording pass; `flow` is the finished
    dataflow result for the same CFG."""
    t0 = time.perf_counter()
    result = TaintResult()
    if flow.incomplete or not cfg.blocks:
        result.incomplete = flow.incomplete
        result.wall_ms = round((time.perf_counter() - t0) * 1e3, 3)
        return result

    acc = _Accumulators()
    entry = cfg.starts[0]
    jumpdest_starts = [s for s in cfg.starts if cfg.blocks[s].is_jumpdest]
    in_states: Dict[int, TaintState] = {}
    visits = 0

    for _round in range(ACCUM_ROUNDS_CAP):
        acc.dirty = False
        in_states = {entry: TaintState.empty()}
        work: List[int] = [entry]
        broadcast_done = False
        while work:
            visits += 1
            if visits > TAINT_VISIT_CAP:
                result.incomplete = True
                log.debug(
                    "taint visit cap hit (%d blocks); opcode-screen "
                    "fallback",
                    len(cfg.blocks),
                )
                result.wall_ms = round(
                    (time.perf_counter() - t0) * 1e3, 3
                )
                return result
            start = work.pop()
            out_state = transfer(cfg.blocks[start], in_states[start], acc)
            successors, broadcast = _successors(
                cfg, flow, cfg.blocks[start]
            )
            if broadcast and not broadcast_done:
                broadcast_done = True
                unknown = TaintState.unknown()
                for s in jumpdest_starts:
                    merged = join(in_states.get(s), unknown)
                    if (
                        s not in in_states
                        or merged.key() != in_states[s].key()
                    ):
                        in_states[s] = merged
                        work.append(s)
            for s in successors:
                if s not in cfg.blocks:
                    continue
                merged = join(in_states.get(s), out_state)
                if s not in in_states or merged.key() != in_states[s].key():
                    in_states[s] = merged
                    work.append(s)
        if not acc.dirty:
            break
    else:
        # the accumulators never stabilized (cannot happen with a
        # monotone 4-bit mask — pure backstop)
        result.incomplete = True

    # recording pass over the fixpoint states
    for start, state in in_states.items():
        transfer(cfg.blocks[start], state, acc, result=result)
    result.reachable = set(in_states)
    result.mem_taint = acc.mem
    result.storage_written = dict(acc.storage)
    result.storage_any_taint = acc.storage_any
    result.wall_ms = round((time.perf_counter() - t0) * 1e3, 3)
    return result
