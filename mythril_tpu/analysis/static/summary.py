"""StaticSummary: the per-code-hash product of the static layer.

Built once per code hash (module-level LRU; the service engine
additionally caches summaries in its own code LRU) and consumed by:

- `laser/batch/seeds.py` — `dead_selectors` drops dispatcher seeds
  for statically-inert functions (logged at DEBUG, counted);
- `laser/batch/explore.py` — `prune_directions()` keeps dead branch
  directions out of the flip frontier;
- `analysis/symbolic.py` / `analysis/security.py` — `features` feeds
  the detector pre-screen;
- `myth lint` / `tools/lint_smoke.py` — `lint_dict()` renders the
  pure static findings + CFG/prune stats.

Soundness contract (the differential acceptance): nothing pruned here
may change the ISSUE set. Dead directions come from constant branch
conditions (the pruned flip would be UNSAT — no witness exists). Dead
selectors are functions whose whole resolved subgraph is *inert*: no
opcode any detector, trigger bank, or evidence bank observes, no
possible stack underflow, no unresolved jump, and only
bounded-operand REVERT/RETURN or STOP terminals — seeding or flipping
into them can only ever produce a clean, write-free halt.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

from mythril_tpu.analysis.static import callgraph as _callgraph
from mythril_tpu.analysis.static.cfg import CFG, recover_cfg
from mythril_tpu.analysis.static.dataflow import DataflowResult, run_dataflow
from mythril_tpu.analysis.static.screen import screen_modules
from mythril_tpu.analysis.static.taint import (
    TAINT_ATTACKER,
    TaintResult,
    run_taint,
)
from mythril_tpu.analysis.static.vsa import ValueSets, value_sets

log = logging.getLogger(__name__)

#: `lint_dict()` payload version, pinned by the lint CLI tests. Bump
#: on any key-set change. v2: taint/value-set facts, per-selector
#: fingerprints, resolved call targets, semantic screen split, the
#: taint lint checks, and the schema_version field itself. v3: the
#: cross-contract link block (call-site provenance, proxy
#: classification) and the four link lint checks.
LINT_SCHEMA_VERSION = 3

#: every check `findings()` can emit — the CLI validates `--fail-on`
#: against this set so a typo'd check name errors instead of silently
#: never firing. The link checks live in `callgraph.LINK_CHECKS` so
#: the linker and the lint surface can't drift.
LINT_CHECKS = (
    frozenset(
        [
            "unreachable-code",
            "invalid-jump-target",
            "stack-underflow",
            "dead-branch",
            "inert-function",
            "tainted-jump-target",
            "tainted-delegatecall-target",
            "tx-origin-as-auth",
            "unprotected-selfdestruct",
        ]
    )
    | _callgraph.LINK_CHECKS
)

#: per-selector fingerprint subgraph bound: a dispatcher entry whose
#: resolved subgraph exceeds this is left unfingerprinted (the
#: incremental-reanalysis consumer treats "no fingerprint" as "always
#: re-analyze")
FINGERPRINT_MAX_BLOCKS = 512


def analysis_config_fingerprint(
    modules=None,
    transaction_count: Optional[int] = None,
    solver_timeout: Optional[int] = None,
    create_timeout: Optional[int] = None,
    creating: bool = False,
    extra: Optional[Dict] = None,
) -> str:
    """Content hash of everything VERDICT-relevant about the analysis
    configuration: two runs with the same code and the same fingerprint
    may share a verdict; any knob that could change the issue set must
    be in here. Hashed: the mythril_tpu version, the transaction count,
    the mounted-module set (None = the full registry), the per-query
    solver timeout, the create-tx budget, whether a create transaction
    runs at all, and the static-layer switches (a --no-static-prune
    verdict mounts more modules than a pruned one). Deliberately NOT
    hashed: the execution/wall budgets — they bound completeness, not
    soundness, and keying on them would shatter the store across every
    deadline setting.

    This is the shared key half of the cross-run verdict store
    (mythril_tpu/store) AND the in-memory `summary_for` cache: a
    StaticSummary's applicable-module verdict depends on the module
    registry in force, so the same code under two module sets must not
    alias one cache slot."""
    from mythril_tpu import __version__
    from mythril_tpu.support.support_args import args as _flags

    if solver_timeout is None:
        solver_timeout = getattr(_flags, "solver_timeout", None)
    parts = [
        f"v={__version__}",
        f"tx={2 if transaction_count is None else int(transaction_count)}",
        "mods={}".format(
            "*" if modules is None else ",".join(sorted(modules))
        ),
        f"st={solver_timeout}",
        f"ct={create_timeout}",
        f"create={int(bool(creating))}",
        f"sp={int(bool(getattr(_flags, 'static_prune', True)))}",
        f"sa={int(bool(getattr(_flags, 'static_answer', True)))}",
    ]
    if extra:
        parts.extend(f"{k}={extra[k]}" for k in sorted(extra))
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]

#: opcodes an inert (prunable) subgraph may contain: pure stack/data
#: shuffling plus control flow. Anything a detection module hooks, the
#: device evidence bank records (arith wraps, storage access, calls,
#: env reads), or that can degrade a lane (unbounded memory growth)
#: disqualifies the subgraph.
INERT_OPS = frozenset(
    ["POP", "JUMPDEST", "JUMP", "JUMPI", "STOP", "REVERT", "RETURN",
     "CALLDATALOAD", "CALLDATASIZE", "CALLVALUE", "CODESIZE", "PC", "GAS",
     "ISZERO", "EQ", "LT", "GT", "SLT", "SGT", "AND", "OR", "XOR", "NOT",
     "BYTE", "SHL", "SHR", "SAR"]
    + [f"PUSH{n}" for n in range(1, 33)]
    + [f"DUP{n}" for n in range(1, 17)]
    + [f"SWAP{n}" for n in range(1, 17)]
)
#: inert-subgraph size bound: bigger bodies are kept explorable
INERT_MAX_BLOCKS = 24


class DispatcherEntry:
    """One recovered dispatcher row: PUSH4 sel; EQ; [ISZERO...]
    PUSH target; JUMPI."""

    __slots__ = ("selector", "jumpi_pc", "entry_pc", "entry_taken")

    def __init__(
        self, selector: bytes, jumpi_pc: int, entry_pc: int, entry_taken: bool
    ) -> None:
        self.selector = selector
        self.jumpi_pc = jumpi_pc
        self.entry_pc = entry_pc
        #: the JUMPI direction that ENTERS the function body (False
        #: when an ISZERO inverted the compare and the body is the
        #: fall-through)
        self.entry_taken = entry_taken


class StaticSummary:
    """Everything the static pass established about one bytecode."""

    def __init__(self, code: bytes) -> None:
        t0 = time.perf_counter()
        self.code_hash = "0x" + hashlib.sha256(code).hexdigest()
        self.code_len = len(code)
        self.cfg: CFG = recover_cfg(code)
        self.flow: DataflowResult = run_dataflow(self.cfg)
        self.incomplete = self.flow.incomplete

        self.n_instructions = len(self.cfg.instructions)
        self.n_blocks = len(self.cfg.blocks)
        self.n_jumpis = sum(
            1 for b in self.cfg.blocks.values() if b.terminator == "JUMPI"
        )
        self.reachable_blocks: Set[int] = set(self.flow.reachable)
        self.dead_blocks: Set[int] = (
            set(self.cfg.blocks) - self.reachable_blocks
        )
        self.dead_instructions = sum(
            len(self.cfg.blocks[s]) for s in self.dead_blocks
        )
        #: branch directions proven infeasible by constant folding
        self.dead_directions: Set[Tuple[int, bool]] = set(
            self.flow.dead_directions
        )

        self.features: Set[str] = self._feature_set()
        self.dispatcher: List[DispatcherEntry] = self._recover_dispatcher()
        self.dead_selectors: Set[bytes] = set()
        #: dispatcher directions entering inert functions — pruned
        #: from the flip frontier alongside the infeasible directions
        self.inert_directions: Set[Tuple[int, bool]] = set()
        self._classify_dead_selectors()

        # the attacker-taint fixpoint + its value-set distillation
        # (taint.py / vsa.py): the semantic half of the detector
        # screen, the static-answer triage predicate, and the facts
        # behind the taint lint checks. Failure is a conservative
        # fallback (`taint=None` -> opcode screen decides), never an
        # error surface.
        self.taint: Optional[TaintResult] = None
        try:
            self.taint = run_taint(self.cfg, self.flow)
        except Exception:
            log.debug("taint pass failed; opcode-screen fallback",
                      exc_info=True)
        self.vsa: ValueSets = value_sets(self.taint, code)
        #: per-selector content hashes of each function's reachable
        #: subgraph — the dedup key incremental re-analysis (ROADMAP
        #: item 3) diffs against
        self.function_fingerprints: Dict[str, str] = (
            self._function_fingerprints()
        )
        #: per-contract half of the cross-contract linker: typed call
        #: sites with target provenance + proxy classification. None
        #: only if the link pass itself fails (linking degrades, the
        #: summary never does).
        self.link = None
        try:
            self.link = _callgraph.link_node(code, self)
        except Exception:
            log.debug("link pass failed; summary stays unlinked",
                      exc_info=True)

        #: mutable prune observability (seeds.py increments)
        self.seeds_dropped = 0
        self.wall_ms = round((time.perf_counter() - t0) * 1e3, 3)

    # -- derived feeds --------------------------------------------------
    def prune_directions(self) -> Set[Tuple[int, bool]]:
        """(jumpi_pc, taken) directions the explorer must never spend
        a flip on: infeasible (constant condition) plus inert
        (dispatcher entry of a statically-dead function)."""
        return self.dead_directions | self.inert_directions

    def applicable_modules(
        self, semantic: bool = True
    ) -> Tuple[List[str], List[str]]:
        """(applicable, skipped) detection-module class names.

        `semantic=True` (default) layers the per-module sink
        predicates over the opcode signatures; `semantic=False` is
        the opcode-only view (the bench reports both rates)."""
        if not semantic:
            return screen_modules(self.features)
        return screen_modules(
            self.features, taint=self.taint, vsa=self.vsa
        )

    @property
    def static_answerable(self) -> bool:
        """True when the semantic screen proves that NO detection
        module can fire on this code: the static-answer triage tier
        settles such a contract with an empty issue set at service
        admission / corpus dispatch, without ever touching the device.
        Requires a COMPLETE taint fixpoint — any bail keeps the
        contract on the full path."""
        if self.incomplete or self.taint is None or self.taint.incomplete:
            return False
        applicable, _skipped = self.applicable_modules()
        return not applicable

    @property
    def prune_units(self) -> int:
        return (
            len(self.dead_directions)
            + len(self.inert_directions)
            + len(self.dead_selectors)
            + len(self.dead_blocks)
        )

    @property
    def total_units(self) -> int:
        return 2 * self.n_jumpis + len(self.dispatcher) + self.n_blocks

    @property
    def prune_rate(self) -> float:
        total = self.total_units
        return round(self.prune_units / total, 4) if total else 0.0

    # -- construction helpers -------------------------------------------
    def _feature_set(self) -> Set[str]:
        if self.incomplete:
            # conservative: the whole instruction stream counts
            return {ins.opcode for ins in self.cfg.instructions}
        return {
            ins.opcode
            for start in self.reachable_blocks
            for ins in self.cfg.blocks[start].instructions
        }

    def _recover_dispatcher(self) -> List[DispatcherEntry]:
        """The Solidity selector-compare idiom, inversion-aware."""
        out: List[DispatcherEntry] = []
        instructions = self.cfg.instructions
        for i, ins in enumerate(instructions):
            if ins.opcode != "PUSH4" or not ins.argument:
                continue
            if i + 1 >= len(instructions) or instructions[i + 1].opcode != "EQ":
                continue
            inverted = False
            target_pc = None
            jumpi_pc = None
            for j in range(i + 2, min(i + 6, len(instructions))):
                op = instructions[j].opcode
                if op == "ISZERO":
                    inverted = not inverted
                elif op.startswith("PUSH"):
                    if (
                        j + 1 < len(instructions)
                        and instructions[j + 1].opcode == "JUMPI"
                    ):
                        target_pc = int(instructions[j].argument, 16)
                        jumpi_pc = instructions[j + 1].address
                    break
                else:
                    break
            if jumpi_pc is None or target_pc is None:
                continue
            selector = bytes.fromhex(ins.argument[2:].rjust(8, "0"))
            if inverted:
                # JUMPI skips PAST the body on mismatch: the function
                # entry is the fall-through
                nxt = self.cfg.block_after(
                    self.cfg.blocks[
                        max(
                            s
                            for s in self.cfg.starts
                            if s <= jumpi_pc
                        )
                    ].start
                )
                if nxt is None:
                    continue
                out.append(DispatcherEntry(selector, jumpi_pc, nxt.start, False))
            else:
                out.append(DispatcherEntry(selector, jumpi_pc, target_pc, True))
        return out

    def _classify_dead_selectors(self) -> None:
        if self.incomplete:
            return
        for entry in self.dispatcher:
            if self._subgraph_inert(entry.entry_pc):
                self.dead_selectors.add(entry.selector)
                self.inert_directions.add((entry.jumpi_pc, entry.entry_taken))

    def _subgraph_inert(self, entry_pc: int) -> bool:
        """True when every path from `entry_pc` over resolved edges is
        observable-effect-free (see module docstring)."""
        if entry_pc not in self.cfg.blocks:
            return False
        seen: Set[int] = set()
        work = [entry_pc]
        while work:
            start = work.pop()
            if start in seen:
                continue
            seen.add(start)
            if len(seen) > INERT_MAX_BLOCKS:
                return False
            block = self.cfg.blocks[start]
            if (
                start in self.flow.underflow_blocks
                or start in self.flow.possible_underflow_blocks
            ):
                return False
            for ins in block.instructions:
                if ins.opcode not in INERT_OPS:
                    return False
            terminator = block.terminator
            if terminator in ("REVERT", "RETURN"):
                if not self._halt_args_bounded(block):
                    return False
                continue
            if terminator == "STOP":
                continue
            if terminator in ("JUMP", "JUMPI"):
                pc = block.end
                if pc in self.flow.unresolved_jumps or pc in self.flow.invalid_jumps:
                    return False
                target = self.flow.resolved_jumps.get(pc)
                if target is None:
                    # block unreachable at fixpoint (no recorded jump
                    # facts): treat as not provably inert
                    return False
                dead = {
                    d for p, d in self.dead_directions if p == pc
                }
                if not (terminator == "JUMPI" and True in dead):
                    work.append(target)
                if terminator == "JUMPI" and False not in dead:
                    nxt = self.cfg.block_after(start)
                    if nxt is None:
                        return False
                    work.append(nxt.start)
                continue
            if terminator == "FALL":
                nxt = self.cfg.block_after(start)
                if nxt is None:
                    return False
                work.append(nxt.start)
                continue
            return False  # ASSERT_FAIL / SUICIDE / INVALID / unknown
        return True

    def _halt_args_bounded(self, block) -> bool:
        """REVERT/RETURN operands must be small constants (or DUPed
        zeros) so the halt cannot expand memory into a degraded lane —
        the `PUSH1 0 DUP1 REVERT` compiler shape and friends."""
        body = block.instructions[:-1]
        tail = body[-2:]
        if len(tail) < 2:
            return False
        for ins in tail:
            if ins.opcode.startswith("PUSH"):
                if int(ins.argument or "0", 16) > 4096:
                    return False
            elif not ins.opcode.startswith("DUP"):
                return False
        return True

    def _function_fingerprints(self) -> Dict[str, str]:
        """selector hex -> content hash of the function's reachable
        subgraph (blocks discovered over resolved edges from the
        dispatcher entry, dead directions honored; bytes hashed are
        each block's opcode names + immediates in block-start order).
        An entry whose subgraph hits an unresolved jump or the block
        cap gets NO fingerprint — "content unknown, always
        re-analyze"."""
        if self.incomplete:
            return {}
        out: Dict[str, str] = {}
        for entry in self.dispatcher:
            blocks = self._subgraph_blocks(entry.entry_pc)
            if blocks is None:
                continue
            digest = hashlib.sha256()
            for start in sorted(blocks):
                for ins in self.cfg.blocks[start].instructions:
                    digest.update(ins.opcode.encode())
                    if ins.argument:
                        digest.update(ins.argument.encode())
            out["0x" + entry.selector.hex()] = digest.hexdigest()[:16]
        return out

    def selector_subgraphs(self) -> Dict[str, List[Tuple[int, int]]]:
        """selector hex -> sorted [start, end] byte spans of the
        blocks in that function's resolved subgraph (the same blocks
        `_function_fingerprints` hashes). The verdict store's
        incremental diff uses these spans to attribute banked issues
        and covered branches to selectors: an address inside exactly
        one selector's spans belongs to that function; addresses in
        shared or dispatcher code attribute to no selector and stay
        conservative. Entries without a bounded subgraph are absent —
        same "content unknown" contract as the fingerprints."""
        out: Dict[str, List[Tuple[int, int]]] = {}
        if self.incomplete:
            return out
        for entry in self.dispatcher:
            blocks = self._subgraph_blocks(entry.entry_pc)
            if blocks is None:
                continue
            out["0x" + entry.selector.hex()] = sorted(
                (start, self.cfg.blocks[start].end)
                for start in blocks
            )
        return out

    def selector_entry_directions(self) -> Dict[str, Tuple[int, bool]]:
        """selector hex -> the (jumpi_pc, taken) dispatcher direction
        that ENTERS the function body — what the incremental
        re-analysis masks to keep an unchanged selector's flips out of
        the frontier."""
        return {
            "0x" + entry.selector.hex(): (entry.jumpi_pc, entry.entry_taken)
            for entry in self.dispatcher
        }

    def _subgraph_blocks(self, entry_pc: int) -> Optional[Set[int]]:
        """Block starts reachable from `entry_pc` over RESOLVED edges,
        or None when the subgraph cannot be bounded (unresolved jump /
        cap). Same traversal discipline as `_subgraph_inert`, without
        the opcode restrictions."""
        if entry_pc not in self.cfg.blocks:
            return None
        seen: Set[int] = set()
        work = [entry_pc]
        while work:
            start = work.pop()
            if start in seen:
                continue
            seen.add(start)
            if len(seen) > FINGERPRINT_MAX_BLOCKS:
                return None
            block = self.cfg.blocks[start]
            terminator = block.terminator
            if terminator in ("JUMP", "JUMPI"):
                pc = block.end
                if pc in self.flow.unresolved_jumps:
                    return None
                target = self.flow.resolved_jumps.get(pc)
                dead = {
                    d for p, d in self.flow.dead_directions if p == pc
                }
                if target is not None and not (
                    terminator == "JUMPI" and True in dead
                ):
                    if target in self.cfg.blocks:
                        work.append(target)
                if terminator == "JUMPI" and False not in dead:
                    nxt = self.cfg.block_after(start)
                    if nxt is not None:
                        work.append(nxt.start)
            elif terminator == "FALL":
                nxt = self.cfg.block_after(start)
                if nxt is not None:
                    work.append(nxt.start)
        return seen

    # -- rendering ------------------------------------------------------
    def stats(self) -> Dict:
        applicable, skipped = self.applicable_modules()
        opcode_applicable, _ = self.applicable_modules(semantic=False)
        out = {
            "code_hash": self.code_hash,
            "code_len": self.code_len,
            "instructions": self.n_instructions,
            "blocks": self.n_blocks,
            "reachable_blocks": len(self.reachable_blocks),
            "dead_blocks": len(self.dead_blocks),
            "dead_instructions": self.dead_instructions,
            "jumpis": self.n_jumpis,
            "resolved_jumps": len(self.flow.resolved_jumps),
            "unresolved_jumps": len(self.flow.unresolved_jumps),
            "invalid_jumps": len(self.flow.invalid_jumps),
            "dead_directions": len(self.dead_directions),
            "selectors": len(self.dispatcher),
            "dead_selectors": len(self.dead_selectors),
            "underflow_blocks": len(self.flow.underflow_blocks),
            "modules_applicable": len(applicable),
            # the opcode-only count beside the semantic one: the
            # bench's strictly-reduces acceptance reads both
            "modules_applicable_opcode": len(opcode_applicable),
            "modules_skipped": sorted(skipped),
            "modules_skipped_semantic": sorted(
                set(opcode_applicable) - set(applicable)
            ),
            "prune_rate": self.prune_rate,
            "seeds_dropped": self.seeds_dropped,
            "static_answerable": self.static_answerable,
            "incomplete": self.incomplete,
            "wall_ms": self.wall_ms,
            # per-selector subgraph fingerprints + resolved call
            # targets / constant slots: the enabling facts for ROADMAP
            # items 3 (incremental re-analysis) and 4 (cross-contract)
            "function_fingerprints": dict(self.function_fingerprints),
            "fingerprint_count": len(self.function_fingerprints),
        }
        out.update(self.vsa.stats())
        if self.taint is not None:
            out["taint"] = {
                "incomplete": self.taint.incomplete,
                "wall_ms": self.taint.wall_ms,
                "density": self.taint.taint_density,
                "sinks": self.taint.sink_counts(),
                "tainted_sinks": self.taint.tainted_sink_counts(),
                "origin_in_condition": bool(
                    self.taint.origin_condition_pcs
                ),
                "caller_in_condition": bool(
                    self.taint.caller_condition_pcs
                ),
                "arith_unsafe_sites": len(self.taint.arith_unsafe_pcs),
            }
        else:
            out["taint"] = {"incomplete": True}
        if self.link is not None:
            out["link"] = self.link.as_dict()
        return out

    def findings(self) -> List[Dict]:
        """Pure static findings for `myth lint` (informational — the
        lint surface, not security issues)."""
        out: List[Dict] = []
        if self.dead_blocks:
            out.append(
                {
                    "check": "unreachable-code",
                    "detail": (
                        f"{self.dead_instructions} instruction(s) across "
                        f"{len(self.dead_blocks)} block(s) are unreachable "
                        "from the entry point"
                    ),
                    "addresses": sorted(self.dead_blocks)[:16],
                }
            )
        for pc, target in sorted(self.flow.invalid_jumps.items()):
            out.append(
                {
                    "check": "invalid-jump-target",
                    "detail": (
                        f"jump at {pc} targets {target}, which is not a "
                        "valid JUMPDEST (execution there always fails)"
                    ),
                    "addresses": [pc],
                }
            )
        for start in sorted(self.flow.underflow_blocks):
            out.append(
                {
                    "check": "stack-underflow",
                    "detail": (
                        f"block at {start} underflows the stack on every "
                        "path (always-reverting)"
                    ),
                    "addresses": [start],
                }
            )
        for pc, dead_taken in sorted(self.dead_directions):
            direction = "taken" if dead_taken else "fall-through"
            out.append(
                {
                    "check": "dead-branch",
                    "detail": (
                        f"JUMPI at {pc}: the {direction} direction is "
                        "statically infeasible (constant condition)"
                    ),
                    "addresses": [pc],
                }
            )
        for entry in self.dispatcher:
            if entry.selector in self.dead_selectors:
                out.append(
                    {
                        "check": "inert-function",
                        "detail": (
                            f"function 0x{entry.selector.hex()} (entry "
                            f"{entry.entry_pc}) has no observable effect "
                            "(pruned from seeding)"
                        ),
                        "addresses": [entry.entry_pc],
                    }
                )
        out.extend(self._taint_findings())
        if self.link is not None:
            out.extend(self.link.findings())
        return out

    def _taint_findings(self) -> List[Dict]:
        """The taint lint checks: informational flow facts from the
        attacker-taint fixpoint (ATTACKER-bit sinks only — the same
        facts drive the semantic screen, rendered here for humans/CI
        via `myth lint --fail-on`)."""
        taint = self.taint
        if taint is None or taint.incomplete:
            return []
        out: List[Dict] = []
        jump_pcs = taint.tainted_jump_pcs()
        if jump_pcs:
            out.append(
                {
                    "check": "tainted-jump-target",
                    "detail": (
                        f"{len(jump_pcs)} jump(s) whose destination is "
                        "influenced by attacker-controlled input "
                        "(calldata/caller/callvalue)"
                    ),
                    "addresses": jump_pcs[:16],
                }
            )
        dc_pcs = taint.tainted_call_sites(kind="DELEGATECALL")
        if dc_pcs:
            out.append(
                {
                    "check": "tainted-delegatecall-target",
                    "detail": (
                        f"{len(dc_pcs)} DELEGATECALL(s) whose target "
                        "address is influenced by attacker-controlled "
                        "input — callee code executes in this "
                        "contract's storage context"
                    ),
                    "addresses": dc_pcs[:16],
                }
            )
        if taint.origin_condition_pcs:
            out.append(
                {
                    "check": "tx-origin-as-auth",
                    "detail": (
                        "tx.origin reaches "
                        f"{len(taint.origin_condition_pcs)} branch "
                        "guard(s) — origin-based authorization is "
                        "phishable; use msg.sender"
                    ),
                    "addresses": sorted(taint.origin_condition_pcs)[:16],
                }
            )
        if taint.selfdestruct_sites and not (
            taint.caller_condition_pcs or taint.origin_condition_pcs
        ):
            out.append(
                {
                    "check": "unprotected-selfdestruct",
                    "detail": (
                        "SELFDESTRUCT is reachable and no branch in "
                        "the contract compares msg.sender or "
                        "tx.origin — nothing gates who may kill it"
                    ),
                    "addresses": sorted(taint.selfdestruct_sites)[:16],
                }
            )
        return out

    def lint_dict(self, name: str = "") -> Dict:
        out = {"contract": name} if name else {}
        out["schema_version"] = LINT_SCHEMA_VERSION
        out.update(self.stats())
        out["findings"] = self.findings()
        return out


# ---------------------------------------------------------------------------
# per-code-hash cache
# ---------------------------------------------------------------------------
_CACHE: "OrderedDict[str, StaticSummary]" = OrderedDict()
_CACHE_CAP = 256
#: the cache is shared across threads (service HTTP admission, wave
#: thread, host-pool workers); one lock keeps the OrderedDict sane
_CACHE_LOCK = threading.Lock()
_HITS = 0
_MISSES = 0


def _as_bytes(code) -> bytes:
    if isinstance(code, bytes):
        return code
    code = code or ""
    if code.startswith("0x"):
        code = code[2:]
    from mythril_tpu.disassembler.asm import safe_decode

    return safe_decode(code)


def analyze_bytecode(code) -> StaticSummary:
    """Uncached static analysis of bytecode (bytes or hex str)."""
    return StaticSummary(_as_bytes(code))


def summary_for(code, config_fp: Optional[str] = None) -> StaticSummary:
    """Cached static analysis (thread-safe). The cache key is
    (code hash, analysis-config fingerprint): a summary's
    applicable-module/static-answerable VERDICT depends on the module
    set and static flags in force, so the same code under two configs
    must occupy two slots — the same key discipline the persistent
    verdict store uses. `config_fp` defaults to the current global
    configuration's fingerprint."""
    global _HITS, _MISSES
    raw = _as_bytes(code)
    if config_fp is None:
        config_fp = analysis_config_fingerprint()
    key = hashlib.sha256(raw).hexdigest() + ":" + config_fp
    with _CACHE_LOCK:
        hit = _CACHE.get(key)
        if hit is not None:
            _HITS += 1
            _CACHE.move_to_end(key)
            return hit
    summary = StaticSummary(raw)
    with _CACHE_LOCK:
        _MISSES += 1
        _CACHE[key] = summary
        while len(_CACHE) > _CACHE_CAP:
            _CACHE.popitem(last=False)
    return summary


def clear_static_cache() -> None:
    global _HITS, _MISSES
    with _CACHE_LOCK:
        _CACHE.clear()
        _HITS = 0
        _MISSES = 0


def static_cache_stats() -> Dict:
    with _CACHE_LOCK:
        return {"size": len(_CACHE), "hits": _HITS, "misses": _MISSES}
