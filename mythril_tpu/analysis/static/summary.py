"""StaticSummary: the per-code-hash product of the static layer.

Built once per code hash (module-level LRU; the service engine
additionally caches summaries in its own code LRU) and consumed by:

- `laser/batch/seeds.py` — `dead_selectors` drops dispatcher seeds
  for statically-inert functions (logged at DEBUG, counted);
- `laser/batch/explore.py` — `prune_directions()` keeps dead branch
  directions out of the flip frontier;
- `analysis/symbolic.py` / `analysis/security.py` — `features` feeds
  the detector pre-screen;
- `myth lint` / `tools/lint_smoke.py` — `lint_dict()` renders the
  pure static findings + CFG/prune stats.

Soundness contract (the differential acceptance): nothing pruned here
may change the ISSUE set. Dead directions come from constant branch
conditions (the pruned flip would be UNSAT — no witness exists). Dead
selectors are functions whose whole resolved subgraph is *inert*: no
opcode any detector, trigger bank, or evidence bank observes, no
possible stack underflow, no unresolved jump, and only
bounded-operand REVERT/RETURN or STOP terminals — seeding or flipping
into them can only ever produce a clean, write-free halt.
"""

from __future__ import annotations

import hashlib
import logging
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

from mythril_tpu.analysis.static.cfg import CFG, recover_cfg
from mythril_tpu.analysis.static.dataflow import DataflowResult, run_dataflow
from mythril_tpu.analysis.static.screen import screen_modules

log = logging.getLogger(__name__)

#: opcodes an inert (prunable) subgraph may contain: pure stack/data
#: shuffling plus control flow. Anything a detection module hooks, the
#: device evidence bank records (arith wraps, storage access, calls,
#: env reads), or that can degrade a lane (unbounded memory growth)
#: disqualifies the subgraph.
INERT_OPS = frozenset(
    ["POP", "JUMPDEST", "JUMP", "JUMPI", "STOP", "REVERT", "RETURN",
     "CALLDATALOAD", "CALLDATASIZE", "CALLVALUE", "CODESIZE", "PC", "GAS",
     "ISZERO", "EQ", "LT", "GT", "SLT", "SGT", "AND", "OR", "XOR", "NOT",
     "BYTE", "SHL", "SHR", "SAR"]
    + [f"PUSH{n}" for n in range(1, 33)]
    + [f"DUP{n}" for n in range(1, 17)]
    + [f"SWAP{n}" for n in range(1, 17)]
)
#: inert-subgraph size bound: bigger bodies are kept explorable
INERT_MAX_BLOCKS = 24


class DispatcherEntry:
    """One recovered dispatcher row: PUSH4 sel; EQ; [ISZERO...]
    PUSH target; JUMPI."""

    __slots__ = ("selector", "jumpi_pc", "entry_pc", "entry_taken")

    def __init__(
        self, selector: bytes, jumpi_pc: int, entry_pc: int, entry_taken: bool
    ) -> None:
        self.selector = selector
        self.jumpi_pc = jumpi_pc
        self.entry_pc = entry_pc
        #: the JUMPI direction that ENTERS the function body (False
        #: when an ISZERO inverted the compare and the body is the
        #: fall-through)
        self.entry_taken = entry_taken


class StaticSummary:
    """Everything the static pass established about one bytecode."""

    def __init__(self, code: bytes) -> None:
        t0 = time.perf_counter()
        self.code_hash = "0x" + hashlib.sha256(code).hexdigest()
        self.code_len = len(code)
        self.cfg: CFG = recover_cfg(code)
        self.flow: DataflowResult = run_dataflow(self.cfg)
        self.incomplete = self.flow.incomplete

        self.n_instructions = len(self.cfg.instructions)
        self.n_blocks = len(self.cfg.blocks)
        self.n_jumpis = sum(
            1 for b in self.cfg.blocks.values() if b.terminator == "JUMPI"
        )
        self.reachable_blocks: Set[int] = set(self.flow.reachable)
        self.dead_blocks: Set[int] = (
            set(self.cfg.blocks) - self.reachable_blocks
        )
        self.dead_instructions = sum(
            len(self.cfg.blocks[s]) for s in self.dead_blocks
        )
        #: branch directions proven infeasible by constant folding
        self.dead_directions: Set[Tuple[int, bool]] = set(
            self.flow.dead_directions
        )

        self.features: Set[str] = self._feature_set()
        self.dispatcher: List[DispatcherEntry] = self._recover_dispatcher()
        self.dead_selectors: Set[bytes] = set()
        #: dispatcher directions entering inert functions — pruned
        #: from the flip frontier alongside the infeasible directions
        self.inert_directions: Set[Tuple[int, bool]] = set()
        self._classify_dead_selectors()

        #: mutable prune observability (seeds.py increments)
        self.seeds_dropped = 0
        self.wall_ms = round((time.perf_counter() - t0) * 1e3, 3)

    # -- derived feeds --------------------------------------------------
    def prune_directions(self) -> Set[Tuple[int, bool]]:
        """(jumpi_pc, taken) directions the explorer must never spend
        a flip on: infeasible (constant condition) plus inert
        (dispatcher entry of a statically-dead function)."""
        return self.dead_directions | self.inert_directions

    def applicable_modules(self) -> Tuple[List[str], List[str]]:
        """(applicable, skipped) detection-module class names."""
        return screen_modules(self.features)

    @property
    def prune_units(self) -> int:
        return (
            len(self.dead_directions)
            + len(self.inert_directions)
            + len(self.dead_selectors)
            + len(self.dead_blocks)
        )

    @property
    def total_units(self) -> int:
        return 2 * self.n_jumpis + len(self.dispatcher) + self.n_blocks

    @property
    def prune_rate(self) -> float:
        total = self.total_units
        return round(self.prune_units / total, 4) if total else 0.0

    # -- construction helpers -------------------------------------------
    def _feature_set(self) -> Set[str]:
        if self.incomplete:
            # conservative: the whole instruction stream counts
            return {ins.opcode for ins in self.cfg.instructions}
        return {
            ins.opcode
            for start in self.reachable_blocks
            for ins in self.cfg.blocks[start].instructions
        }

    def _recover_dispatcher(self) -> List[DispatcherEntry]:
        """The Solidity selector-compare idiom, inversion-aware."""
        out: List[DispatcherEntry] = []
        instructions = self.cfg.instructions
        for i, ins in enumerate(instructions):
            if ins.opcode != "PUSH4" or not ins.argument:
                continue
            if i + 1 >= len(instructions) or instructions[i + 1].opcode != "EQ":
                continue
            inverted = False
            target_pc = None
            jumpi_pc = None
            for j in range(i + 2, min(i + 6, len(instructions))):
                op = instructions[j].opcode
                if op == "ISZERO":
                    inverted = not inverted
                elif op.startswith("PUSH"):
                    if (
                        j + 1 < len(instructions)
                        and instructions[j + 1].opcode == "JUMPI"
                    ):
                        target_pc = int(instructions[j].argument, 16)
                        jumpi_pc = instructions[j + 1].address
                    break
                else:
                    break
            if jumpi_pc is None or target_pc is None:
                continue
            selector = bytes.fromhex(ins.argument[2:].rjust(8, "0"))
            if inverted:
                # JUMPI skips PAST the body on mismatch: the function
                # entry is the fall-through
                nxt = self.cfg.block_after(
                    self.cfg.blocks[
                        max(
                            s
                            for s in self.cfg.starts
                            if s <= jumpi_pc
                        )
                    ].start
                )
                if nxt is None:
                    continue
                out.append(DispatcherEntry(selector, jumpi_pc, nxt.start, False))
            else:
                out.append(DispatcherEntry(selector, jumpi_pc, target_pc, True))
        return out

    def _classify_dead_selectors(self) -> None:
        if self.incomplete:
            return
        for entry in self.dispatcher:
            if self._subgraph_inert(entry.entry_pc):
                self.dead_selectors.add(entry.selector)
                self.inert_directions.add((entry.jumpi_pc, entry.entry_taken))

    def _subgraph_inert(self, entry_pc: int) -> bool:
        """True when every path from `entry_pc` over resolved edges is
        observable-effect-free (see module docstring)."""
        if entry_pc not in self.cfg.blocks:
            return False
        seen: Set[int] = set()
        work = [entry_pc]
        while work:
            start = work.pop()
            if start in seen:
                continue
            seen.add(start)
            if len(seen) > INERT_MAX_BLOCKS:
                return False
            block = self.cfg.blocks[start]
            if (
                start in self.flow.underflow_blocks
                or start in self.flow.possible_underflow_blocks
            ):
                return False
            for ins in block.instructions:
                if ins.opcode not in INERT_OPS:
                    return False
            terminator = block.terminator
            if terminator in ("REVERT", "RETURN"):
                if not self._halt_args_bounded(block):
                    return False
                continue
            if terminator == "STOP":
                continue
            if terminator in ("JUMP", "JUMPI"):
                pc = block.end
                if pc in self.flow.unresolved_jumps or pc in self.flow.invalid_jumps:
                    return False
                target = self.flow.resolved_jumps.get(pc)
                if target is None:
                    # block unreachable at fixpoint (no recorded jump
                    # facts): treat as not provably inert
                    return False
                dead = {
                    d for p, d in self.dead_directions if p == pc
                }
                if not (terminator == "JUMPI" and True in dead):
                    work.append(target)
                if terminator == "JUMPI" and False not in dead:
                    nxt = self.cfg.block_after(start)
                    if nxt is None:
                        return False
                    work.append(nxt.start)
                continue
            if terminator == "FALL":
                nxt = self.cfg.block_after(start)
                if nxt is None:
                    return False
                work.append(nxt.start)
                continue
            return False  # ASSERT_FAIL / SUICIDE / INVALID / unknown
        return True

    def _halt_args_bounded(self, block) -> bool:
        """REVERT/RETURN operands must be small constants (or DUPed
        zeros) so the halt cannot expand memory into a degraded lane —
        the `PUSH1 0 DUP1 REVERT` compiler shape and friends."""
        body = block.instructions[:-1]
        tail = body[-2:]
        if len(tail) < 2:
            return False
        for ins in tail:
            if ins.opcode.startswith("PUSH"):
                if int(ins.argument or "0", 16) > 4096:
                    return False
            elif not ins.opcode.startswith("DUP"):
                return False
        return True

    # -- rendering ------------------------------------------------------
    def stats(self) -> Dict:
        applicable, skipped = self.applicable_modules()
        return {
            "code_hash": self.code_hash,
            "code_len": self.code_len,
            "instructions": self.n_instructions,
            "blocks": self.n_blocks,
            "reachable_blocks": len(self.reachable_blocks),
            "dead_blocks": len(self.dead_blocks),
            "dead_instructions": self.dead_instructions,
            "jumpis": self.n_jumpis,
            "resolved_jumps": len(self.flow.resolved_jumps),
            "unresolved_jumps": len(self.flow.unresolved_jumps),
            "invalid_jumps": len(self.flow.invalid_jumps),
            "dead_directions": len(self.dead_directions),
            "selectors": len(self.dispatcher),
            "dead_selectors": len(self.dead_selectors),
            "underflow_blocks": len(self.flow.underflow_blocks),
            "modules_applicable": len(applicable),
            "modules_skipped": sorted(skipped),
            "prune_rate": self.prune_rate,
            "seeds_dropped": self.seeds_dropped,
            "incomplete": self.incomplete,
            "wall_ms": self.wall_ms,
        }

    def findings(self) -> List[Dict]:
        """Pure static findings for `myth lint` (informational — the
        lint surface, not security issues)."""
        out: List[Dict] = []
        if self.dead_blocks:
            out.append(
                {
                    "check": "unreachable-code",
                    "detail": (
                        f"{self.dead_instructions} instruction(s) across "
                        f"{len(self.dead_blocks)} block(s) are unreachable "
                        "from the entry point"
                    ),
                    "addresses": sorted(self.dead_blocks)[:16],
                }
            )
        for pc, target in sorted(self.flow.invalid_jumps.items()):
            out.append(
                {
                    "check": "invalid-jump-target",
                    "detail": (
                        f"jump at {pc} targets {target}, which is not a "
                        "valid JUMPDEST (execution there always fails)"
                    ),
                    "addresses": [pc],
                }
            )
        for start in sorted(self.flow.underflow_blocks):
            out.append(
                {
                    "check": "stack-underflow",
                    "detail": (
                        f"block at {start} underflows the stack on every "
                        "path (always-reverting)"
                    ),
                    "addresses": [start],
                }
            )
        for pc, dead_taken in sorted(self.dead_directions):
            direction = "taken" if dead_taken else "fall-through"
            out.append(
                {
                    "check": "dead-branch",
                    "detail": (
                        f"JUMPI at {pc}: the {direction} direction is "
                        "statically infeasible (constant condition)"
                    ),
                    "addresses": [pc],
                }
            )
        for entry in self.dispatcher:
            if entry.selector in self.dead_selectors:
                out.append(
                    {
                        "check": "inert-function",
                        "detail": (
                            f"function 0x{entry.selector.hex()} (entry "
                            f"{entry.entry_pc}) has no observable effect "
                            "(pruned from seeding)"
                        ),
                        "addresses": [entry.entry_pc],
                    }
                )
        return out

    def lint_dict(self, name: str = "") -> Dict:
        out = {"contract": name} if name else {}
        out.update(self.stats())
        out["findings"] = self.findings()
        return out


# ---------------------------------------------------------------------------
# per-code-hash cache
# ---------------------------------------------------------------------------
_CACHE: "OrderedDict[str, StaticSummary]" = OrderedDict()
_CACHE_CAP = 256
_HITS = 0
_MISSES = 0


def _as_bytes(code) -> bytes:
    if isinstance(code, bytes):
        return code
    code = code or ""
    if code.startswith("0x"):
        code = code[2:]
    from mythril_tpu.disassembler.asm import safe_decode

    return safe_decode(code)


def analyze_bytecode(code) -> StaticSummary:
    """Uncached static analysis of bytecode (bytes or hex str)."""
    return StaticSummary(_as_bytes(code))


def summary_for(code) -> StaticSummary:
    """Cached-by-code-hash static analysis."""
    global _HITS, _MISSES
    raw = _as_bytes(code)
    key = hashlib.sha256(raw).hexdigest()
    hit = _CACHE.get(key)
    if hit is not None:
        _HITS += 1
        _CACHE.move_to_end(key)
        return hit
    _MISSES += 1
    summary = StaticSummary(raw)
    _CACHE[key] = summary
    while len(_CACHE) > _CACHE_CAP:
        _CACHE.popitem(last=False)
    return summary


def clear_static_cache() -> None:
    global _HITS, _MISSES
    _CACHE.clear()
    _HITS = 0
    _MISSES = 0


def static_cache_stats() -> Dict:
    return {"size": len(_CACHE), "hits": _HITS, "misses": _MISSES}
