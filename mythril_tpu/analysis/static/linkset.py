"""LinkSet: the corpus-level cross-contract static linker.

Joins the per-contract `callgraph.ContractNode` facts into one typed
inter-contract graph:

- **nodes** are codehashes (per-selector sub-facts ride on each node's
  dispatcher attribution);
- **edges** are the typed call sites, resolved to a callee codehash
  through the **address book** — deployment addresses declared by
  corpus row names (``name@0x<40 hex>``), constant/immutable targets,
  minimal-proxy literals, runtime slot bindings, and init-code
  bindings (`implementation_from_init_code`).

On top of the resolved graph:

- **escape summaries** — per (contract, selector): which provenance
  bits can flow OUT into callee calldata, computed bottom-up over the
  Tarjan SCC condensation (callees first). Cycles and unresolved
  edges widen to TAINT_ANY — convergent by construction, monotone by
  the 4-bit mask.
- **proxy pairing + storage-collision diff** — each proxy-slot /
  minimal-proxy DELEGATECALL bound to a callee pairs the two
  contracts; the pair's constant storage footprints (minus the named
  proxy slots) are intersected for collision risk.
- **linked fingerprints** — per selector,
  ``H(base fingerprint | sorted resolved callee-closure codehashes)``:
  the store's incremental planner diffs these so an implementation
  upgrade behind an unchanged proxy invalidates exactly the selectors
  whose callee closure moved. Selectors whose closure crosses an
  unresolved edge or a cycle get a named problem (``link-unresolved``
  / ``link-cycle``) instead of a fingerprint.
- **arena co-location plan** — per entry contract, the resolved
  callee codehash closure: the exact artifact the device engine's
  multi-account arena work pre-loads before dispatch (ROADMAP 1).

Pure host work over already-computed summaries — no jax — so
`myth graph` stays a sub-second line-rate tool.
"""

from __future__ import annotations

import hashlib
import logging
import re
import time
from typing import Dict, List, Optional, Set, Tuple

from mythril_tpu.analysis.static.callgraph import (
    ADDRESSABLE_PROVENANCE,
    ContractNode,
    PROV_MINIMAL_PROXY,
    PROV_PROXY_SLOT,
    PROXY_SLOTS,
    _bump,
    implementation_from_init_code,
    link_node,
)

log = logging.getLogger(__name__)

#: `myth graph --json` / jsonv2 link-meta payload version
GRAPH_SCHEMA_VERSION = 1

#: call sites not inside exactly one selector's spans attribute to
#: this pseudo-selector: they ride every selector's closure (shared /
#: dispatcher / fallback code runs for any selector)
SHARED_SELECTOR = "*"

_NAME_ADDR = re.compile(r"@0x([0-9a-fA-F]{40})")


def address_from_name(name: str) -> Optional[int]:
    """The deployment address a corpus row/file name declares
    (``anything@0x<40 hex>``, the part after ``@`` wins), or None."""
    match = _NAME_ADDR.search(name or "")
    return int(match.group(1), 16) if match else None


class LinkSet:
    """The multi-contract container + resolution passes."""

    def __init__(self) -> None:
        #: code_hash -> ContractNode
        self.nodes: Dict[str, ContractNode] = {}
        #: code_hash -> first row name seen (the graph's display key)
        self.names: Dict[str, str] = {}
        #: deployment address -> code_hash (last add wins: an upgrade
        #: is "same address, new code" — exactly the invalidation the
        #: linked fingerprints exist to catch)
        self.book: Dict[int, str] = {}
        #: code_hash -> selector -> base function fingerprint
        self.base_fps: Dict[str, Dict[str, str]] = {}
        #: code_hash -> init-code implementation binding
        self.init_bindings: Dict[str, int] = {}
        self._resolved: Optional[Dict] = None

    # -- construction ---------------------------------------------------
    def add(
        self,
        name: str,
        code: bytes,
        summary,
        address: Optional[int] = None,
        init_code=None,
    ) -> ContractNode:
        """Register one contract. `address` overrides the name-declared
        deployment address; `init_code` (hex or bytes) feeds the
        init-code implementation binding."""
        node = getattr(summary, "link", None)
        if node is None:
            node = link_node(code, summary)
        self._resolved = None
        self.nodes[node.code_hash] = node
        self.names.setdefault(node.code_hash, name)
        addr = address if address is not None else address_from_name(name)
        if addr is not None:
            self.book[addr] = node.code_hash
        self.base_fps[node.code_hash] = dict(
            getattr(summary, "function_fingerprints", {}) or {}
        )
        if init_code:
            impl = implementation_from_init_code(init_code)
            if impl is not None:
                self.init_bindings[node.code_hash] = impl
        return node

    # -- resolution -----------------------------------------------------
    def resolve(self) -> Dict:
        """Run (or return the cached) resolution: edges, SCCs, escape
        fixpoint, proxy pairs, collisions, linked fingerprints."""
        if self._resolved is not None:
            return self._resolved
        t0 = time.perf_counter()
        edges: List[Dict] = []
        adjacency: Dict[str, Set[str]] = {ch: set() for ch in self.nodes}
        for ch, node in self.nodes.items():
            for site in node.call_sites:
                address = site.target_address
                if (
                    address is None
                    and site.provenance == PROV_PROXY_SLOT
                ):
                    address = self.init_bindings.get(ch)
                callee = (
                    self.book.get(address) if address is not None else None
                )
                edge = {
                    "caller": ch,
                    "pc": site.pc,
                    "kind": site.kind,
                    "selector": site.selector or SHARED_SELECTOR,
                    "provenance": site.provenance,
                    "target_address": (
                        f"0x{address:040x}" if address is not None else None
                    ),
                    "callee": callee,
                    "resolved": callee is not None,
                }
                edges.append(edge)
                if callee is not None:
                    adjacency[ch].add(callee)
                    if callee not in adjacency:
                        adjacency[callee] = set()

        sccs = _tarjan(adjacency)
        cyclic: Set[str] = set()
        for members in sccs:
            if len(members) > 1:
                cyclic.update(members)
        for ch in adjacency:
            if ch in adjacency[ch]:  # self-loop: A resolves to itself
                cyclic.add(ch)

        escapes, widened = self._escape_fixpoint(edges, sccs, cyclic)
        pairs, collisions = self._pair_proxies(edges)
        linked_fps, link_problems = self._linked_fingerprints(
            edges, adjacency, cyclic
        )
        _bump("escape_widened", widened)
        _bump("pairs", len(pairs))
        _bump("collisions", len(collisions))

        resolved_edges = sum(1 for e in edges if e["resolved"])
        addressable = sum(
            1
            for e in edges
            if e["provenance"] in ADDRESSABLE_PROVENANCE
        )
        self._resolved = {
            "edges": edges,
            "adjacency": adjacency,
            "cyclic": cyclic,
            "escapes": escapes,
            "widened": widened,
            "pairs": pairs,
            "collisions": collisions,
            "linked_fingerprints": linked_fps,
            "link_problems": link_problems,
            "stats": {
                "nodes": len(self.nodes),
                "edges": len(edges),
                "edges_resolved": resolved_edges,
                "edges_addressable": addressable,
                "resolve_rate": (
                    round(resolved_edges / len(edges), 4) if edges else 1.0
                ),
                "proxies": sum(
                    1 for n in self.nodes.values() if n.is_proxy
                ),
                "proxy_pairs": len(pairs),
                "collisions": len(collisions),
                "escape_widened": widened,
                "wall_ms": round((time.perf_counter() - t0) * 1e3, 3),
            },
        }
        return self._resolved

    def _escape_fixpoint(
        self, edges: List[Dict], sccs: List[List[str]], cyclic: Set[str]
    ) -> Tuple[Dict[str, Dict[str, Dict]], int]:
        """Bottom-up escape summaries. Tarjan emits each SCC after
        every SCC reachable from it, so walking the SCC list in
        emission order processes callees before callers — one pass IS
        the fixpoint on the acyclic condensation; cyclic members and
        unresolved edges widen to TAINT_ANY."""
        from mythril_tpu.analysis.static.taint import (
            TAINT_ANY,
            TAINT_ATTACKER,
            TAINT_UNKNOWN,
        )

        by_caller: Dict[str, List[Dict]] = {}
        for edge in edges:
            by_caller.setdefault(edge["caller"], []).append(edge)
        escapes: Dict[str, Dict[str, Dict]] = {}
        totals: Dict[str, int] = {}
        widened = 0
        for members in sccs:
            for ch in members:
                node = self.nodes.get(ch)
                if node is None:
                    totals.setdefault(ch, 0)
                    continue
                selectors = set(node.selectors) or set()
                per_sel: Dict[str, Dict] = {}
                shared_sites = []
                sel_sites: Dict[str, List[Dict]] = {}
                for edge in by_caller.get(ch, []):
                    if edge["selector"] == SHARED_SELECTOR:
                        shared_sites.append(edge)
                    else:
                        sel_sites.setdefault(edge["selector"], []).append(
                            edge
                        )
                        selectors.add(edge["selector"])
                if shared_sites and not selectors:
                    selectors = {SHARED_SELECTOR}
                for sel in sorted(selectors):
                    mask = 0
                    wide = False
                    sites = sel_sites.get(sel, []) + (
                        shared_sites if sel != SHARED_SELECTOR else []
                    )
                    if sel == SHARED_SELECTOR:
                        sites = list(shared_sites)
                    for edge in sites:
                        if node.incomplete or ch in cyclic:
                            mask = TAINT_ANY
                            wide = True
                            break
                        site_mask = (
                            TAINT_ATTACKER
                            if _edge_args_attacker(node, edge)
                            else TAINT_UNKNOWN
                        )
                        if edge["resolved"]:
                            mask |= site_mask | totals.get(
                                edge["callee"], 0
                            )
                        else:
                            mask = TAINT_ANY
                            wide = True
                            break
                    per_sel[sel] = {"mask": mask, "widened": wide}
                    if wide:
                        widened += 1
                if node.guard_return_pcs:
                    for sel in per_sel.values():
                        sel.setdefault("return_to_guard", True)
                escapes[ch] = per_sel
                totals[ch] = 0
                for row in per_sel.values():
                    totals[ch] |= row["mask"]
        return escapes, widened

    def _pair_proxies(
        self, edges: List[Dict]
    ) -> Tuple[List[Dict], List[Dict]]:
        pairs: List[Dict] = []
        collisions: List[Dict] = []
        seen: Set[Tuple[str, str]] = set()
        for edge in edges:
            if edge["kind"] not in ("DELEGATECALL", "CALLCODE"):
                continue
            if edge["provenance"] not in (
                PROV_PROXY_SLOT,
                PROV_MINIMAL_PROXY,
            ):
                continue
            if not edge["resolved"]:
                continue
            proxy_ch, impl_ch = edge["caller"], edge["callee"]
            if (proxy_ch, impl_ch) in seen:
                continue
            seen.add((proxy_ch, impl_ch))
            proxy = self.nodes[proxy_ch]
            impl = self.nodes.get(impl_ch)
            pair = {
                "proxy": proxy_ch,
                "implementation": impl_ch,
                "kind": proxy.proxy_kind or edge["provenance"],
                "upgradeable": proxy.upgradeable,
            }
            pairs.append(pair)
            if impl is None:
                continue
            # storage-collision diff: the proxy's own constant slots
            # (minus the named proxy slots, which are CHOSEN to never
            # collide) against the implementation's written slots —
            # under DELEGATECALL both address the same storage
            proxy_slots = (
                proxy.storage_reads | proxy.storage_writes
            ) - set(PROXY_SLOTS)
            impl_writes = impl.storage_writes - set(PROXY_SLOTS)
            shared = sorted(proxy_slots & impl_writes)
            if shared:
                collisions.append(
                    {
                        "proxy": proxy_ch,
                        "implementation": impl_ch,
                        "slots": [hex(s) for s in shared],
                    }
                )
        return pairs, collisions

    def _linked_fingerprints(
        self,
        edges: List[Dict],
        adjacency: Dict[str, Set[str]],
        cyclic: Set[str],
    ) -> Tuple[Dict[str, Dict[str, str]], Dict[str, Dict[str, str]]]:
        """code_hash -> selector -> linked fingerprint, plus
        code_hash -> selector -> problem ("link-unresolved" /
        "link-cycle") for selectors whose callee closure cannot be
        pinned. A selector with NO call sites still gets a linked
        fingerprint (= H(base | empty)), so the store's linked entry
        always carries the full selector set."""
        by_caller_sel: Dict[str, Dict[str, List[Dict]]] = {}
        unresolved_callers: Set[str] = set()
        for edge in edges:
            by_caller_sel.setdefault(edge["caller"], {}).setdefault(
                edge["selector"], []
            ).append(edge)
            if not edge["resolved"]:
                unresolved_callers.add(edge["caller"])
        fps: Dict[str, Dict[str, str]] = {}
        problems: Dict[str, Dict[str, str]] = {}
        for ch, base in self.base_fps.items():
            node = self.nodes.get(ch)
            per_sel = by_caller_sel.get(ch, {})
            shared = per_sel.get(SHARED_SELECTOR, [])
            out: Dict[str, str] = {}
            bad: Dict[str, str] = {}
            for sel, base_fp in base.items():
                sites = per_sel.get(sel, []) + shared
                problem = None
                closure: Set[str] = set()
                if node is not None and node.incomplete:
                    problem = "link-unresolved"
                for edge in sites:
                    if problem:
                        break
                    if not edge["resolved"]:
                        problem = "link-unresolved"
                        break
                    closure.add(edge["callee"])
                if problem is None and closure:
                    problem, closure = self._closure(
                        ch, closure, adjacency, cyclic, unresolved_callers
                    )
                if problem:
                    bad[sel] = problem
                    continue
                digest = hashlib.sha256(
                    (base_fp + "|" + ",".join(sorted(closure))).encode()
                ).hexdigest()[:16]
                out[sel] = digest
            fps[ch] = out
            if bad:
                problems[ch] = bad
        return fps, problems

    def _closure(
        self,
        origin: str,
        roots: Set[str],
        adjacency: Dict[str, Set[str]],
        cyclic: Set[str],
        unresolved_callers: Set[str],
    ) -> Tuple[Optional[str], Set[str]]:
        """Transitive resolved-callee closure from `roots`, or a
        problem name. Any member with an unresolved or incomplete
        site taints the whole closure (the codehash set alone no
        longer pins behavior); reaching back to `origin` or any
        cyclic member is a cycle."""
        seen: Set[str] = set()
        work = list(roots)
        while work:
            ch = work.pop()
            if ch in seen:
                continue
            seen.add(ch)
            if ch == origin or ch in cyclic:
                return "link-cycle", set()
            node = self.nodes.get(ch)
            if node is None or node.incomplete:
                return "link-unresolved", set()
            if ch in unresolved_callers:
                return "link-unresolved", set()
            work.extend(adjacency.get(ch, ()))
        return None, seen

    # -- consumer surfaces ----------------------------------------------
    def linked_fingerprints(
        self, code_hash: str
    ) -> Tuple[Dict[str, str], Dict[str, str]]:
        """(selector -> linked fingerprint, selector -> problem) for
        one contract."""
        data = self.resolve()
        return (
            dict(data["linked_fingerprints"].get(code_hash, {})),
            dict(data["link_problems"].get(code_hash, {})),
        )

    def node_meta(self, code_hash: str) -> Optional[Dict]:
        """The compact per-contract link block (jsonv2 meta / routing
        features / triage alerts)."""
        node = self.nodes.get(code_hash)
        if node is None:
            return None
        data = self.resolve()
        out_edges = [
            e for e in data["edges"] if e["caller"] == code_hash
        ]
        escapes = data["escapes"].get(code_hash, {})
        n_sel = max(1, len(escapes) or len(node.selectors) or 1)
        density = round(
            sum(
                1
                for row in escapes.values()
                if row["mask"]
            )
            / n_sel,
            4,
        )
        meta = dict(node.as_dict())
        meta.update(
            {
                "resolved_degree": sum(
                    1 for e in out_edges if e["resolved"]
                ),
                "escape_density": density,
                "escape_widened": sum(
                    1 for row in escapes.values() if row.get("widened")
                ),
                "in_pair": any(
                    code_hash in (p["proxy"], p["implementation"])
                    for p in data["pairs"]
                ),
            }
        )
        return meta

    def arena_plan(self) -> Dict[str, List[str]]:
        """Entry codehash -> sorted resolved callee-codehash closure
        (the multi-account arena's co-location artifact). Entries with
        no resolved callees map to an empty list."""
        data = self.resolve()
        adjacency = data["adjacency"]
        plan: Dict[str, List[str]] = {}
        for ch in self.nodes:
            seen: Set[str] = set()
            work = list(adjacency.get(ch, ()))
            while work:
                nxt = work.pop()
                if nxt in seen or nxt == ch:
                    continue
                seen.add(nxt)
                work.extend(adjacency.get(nxt, ()))
            plan[ch] = sorted(seen)
        return plan

    def findings(self) -> List[Dict]:
        """Corpus-level link findings: every node's single-contract
        checks (tagged with the row name) plus the pair-level
        `proxy-storage-collision` rows."""
        data = self.resolve()
        out: List[Dict] = []
        for ch, node in self.nodes.items():
            for row in node.findings():
                row = dict(row)
                row["contract"] = self.names.get(ch, ch)
                out.append(row)
        for collision in data["collisions"]:
            out.append(
                {
                    "check": "proxy-storage-collision",
                    "contract": self.names.get(
                        collision["proxy"], collision["proxy"]
                    ),
                    "detail": (
                        "proxy and implementation "
                        f"{self.names.get(collision['implementation'], collision['implementation'])}"
                        " both address constant storage slot(s) "
                        f"{', '.join(collision['slots'])} — under "
                        "DELEGATECALL they alias the same storage"
                    ),
                    "addresses": [
                        int(s, 16) for s in collision["slots"]
                    ][:16],
                }
            )
        return out

    def stats(self) -> Dict:
        return dict(self.resolve()["stats"])

    def as_dict(self) -> Dict:
        """The `myth graph --json` payload."""
        data = self.resolve()
        addr_of = {ch: None for ch in self.nodes}
        for addr, ch in self.book.items():
            addr_of[ch] = f"0x{addr:040x}"
        contracts = []
        for ch in sorted(self.nodes, key=lambda c: self.names.get(c, c)):
            node = self.nodes[ch]
            row = {
                "name": self.names.get(ch, ch),
                "address": addr_of.get(ch),
                "selectors": sorted(node.selectors),
                "link": self.node_meta(ch),
                "escape": {
                    sel: dict(rec)
                    for sel, rec in sorted(
                        data["escapes"].get(ch, {}).items()
                    )
                },
                "linked_fingerprints": dict(
                    data["linked_fingerprints"].get(ch, {})
                ),
                "link_problems": dict(
                    data["link_problems"].get(ch, {})
                ),
            }
            contracts.append(row)
        return {
            "schema_version": GRAPH_SCHEMA_VERSION,
            "contracts": contracts,
            "edges": [dict(e) for e in data["edges"]],
            "proxy_pairs": [dict(p) for p in data["pairs"]],
            "collisions": [dict(c) for c in data["collisions"]],
            "arena_plan": {
                self.names.get(ch, ch): callees
                for ch, callees in sorted(self.arena_plan().items())
            },
            "findings": self.findings(),
            "stats": self.stats(),
        }


def _edge_args_attacker(node: ContractNode, edge: Dict) -> bool:
    for site in node.call_sites:
        if site.pc == edge["pc"]:
            return site.args_attacker
    return False


def _tarjan(adjacency: Dict[str, Set[str]]) -> List[List[str]]:
    """Iterative Tarjan SCC. Emission order: every SCC is emitted
    AFTER all SCCs reachable from it (reverse topological order of
    the condensation) — the order the escape fixpoint wants."""
    index: Dict[str, int] = {}
    lowlink: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in sorted(adjacency):
        if root in index:
            continue
        work: List[Tuple[str, iter]] = [(root, iter(sorted(adjacency[root])))]
        index[root] = lowlink[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for succ in it:
                if succ not in adjacency:
                    continue
                if succ not in index:
                    index[succ] = lowlink[succ] = counter[0]
                    counter[0] += 1
                    stack.append(succ)
                    on_stack.add(succ)
                    work.append(
                        (succ, iter(sorted(adjacency[succ])))
                    )
                    advanced = True
                    break
                if succ in on_stack:
                    lowlink[node] = min(lowlink[node], index[succ])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
            if lowlink[node] == index[node]:
                members: List[str] = []
                while True:
                    member = stack.pop()
                    on_stack.discard(member)
                    members.append(member)
                    if member == node:
                        break
                sccs.append(members)
    return sccs


def link_corpus(contracts) -> LinkSet:
    """Build a LinkSet from analyze_corpus's input rows
    ``[(runtime_hex, creation_hex, name), ...]``. Per-row failures
    skip that row — linking degrades coverage, never correctness."""
    from mythril_tpu.analysis.static import summary_for

    linkset = LinkSet()
    for row in contracts:
        try:
            code_hex, creation_hex, name = row
        except (TypeError, ValueError):
            continue
        norm = (
            code_hex[2:] if code_hex.startswith("0x") else code_hex
        )
        if len(norm) < 8:
            continue
        try:
            summary = summary_for(norm)
            linkset.add(
                name,
                bytes.fromhex(norm),
                summary,
                init_code=creation_hex or None,
            )
        except Exception:
            log.debug("link pass skipped %s", name, exc_info=True)
    return linkset
