"""Host-side static bytecode analysis (the pre-dispatch layer).

Four cooperating analyses over one shared IR (the disassembler's
instruction list), run once per code hash BEFORE any arena lane is
seeded or any detection module is mounted:

1. **CFG recovery** (`cfg.py`) — basic blocks + peephole PUSH-const
   jump-target resolution over `disassembler/asm.py` instructions.
   Distinct from the symbolic `laser/ethereum/cfg.py`: that graph is
   built DURING host execution; this one exists before anything runs.
2. **Dataflow** (`dataflow.py`) — abstract stack-height + constant
   lattice worklist over the blocks: resolves computed jumps whose
   targets are stack constants, flags definite stack-underflow and
   const-invalid-jumpdest blocks, and constant-folds JUMPI conditions
   into statically-dead branch directions.
3. **Detector pre-screen** (`screen.py`) — per-module opcode/feature
   signatures over the reachable instruction set, so
   `analysis/security.py` loads only modules that can possibly fire
   on this contract.
4. **Prune feed** (`summary.py` StaticSummary) — consumed by
   `laser/batch/seeds.py` (dispatcher seeds for statically-inert
   functions are dropped) and `laser/batch/explore.py` (dead branch
   directions never enter the flip frontier).

The whole pass is pure host work (no jax, no device): `myth lint`
runs it standalone, `myth analyze`/`myth serve` run it as an always-on
prepass, and the service engine caches summaries by code hash in its
existing LRU beside the dense disassembly rows.

Manticore (arxiv 1907.03890) fronts symbolic exploration with exactly
this kind of CFG recovery; the Blockchain Superoptimizer (arxiv
2005.05912) shows how far pure constant propagation over EVM stack
code reaches without a solver — this layer is the batched-arena
adaptation of both.
"""

from __future__ import annotations

from mythril_tpu.analysis.static.cfg import BasicBlock, recover_cfg
from mythril_tpu.analysis.static.screen import (
    MODULE_SIGNATURES,
    screen_modules,
)
from mythril_tpu.analysis.static.summary import (
    StaticSummary,
    analyze_bytecode,
    clear_static_cache,
    static_cache_stats,
    summary_for,
)


def static_prune_enabled() -> bool:
    """One switch for every consumer (CLI --no-static-prune)."""
    from mythril_tpu.support.support_args import args

    return bool(getattr(args, "static_prune", True))


__all__ = [
    "BasicBlock",
    "MODULE_SIGNATURES",
    "StaticSummary",
    "analyze_bytecode",
    "clear_static_cache",
    "recover_cfg",
    "screen_modules",
    "static_cache_stats",
    "static_prune_enabled",
    "summary_for",
]
