"""Host-side static bytecode analysis (the pre-dispatch layer).

Cooperating analyses over one shared IR (the disassembler's
instruction list), run once per code hash BEFORE any arena lane is
seeded or any detection module is mounted:

1. **CFG recovery** (`cfg.py`) — basic blocks + peephole PUSH-const
   jump-target resolution over `disassembler/asm.py` instructions.
   Distinct from the symbolic `laser/ethereum/cfg.py`: that graph is
   built DURING host execution; this one exists before anything runs.
2. **Dataflow** (`dataflow.py`) — abstract stack-height + constant
   lattice worklist over the blocks: resolves computed jumps whose
   targets are stack constants, flags definite stack-underflow and
   const-invalid-jumpdest blocks, and constant-folds JUMPI conditions
   into statically-dead branch directions.
3. **Attacker-taint fixpoint** (`taint.py`) — a second worklist pass
   propagating an attacker-influence lattice (calldata/caller/
   callvalue/returndata sources; conservative joins through memory,
   storage and the stack window) to the detector sinks: jump targets
   and branch guards, call targets/values, SSTORE slots, SELFDESTRUCT
   beneficiaries, LOG1 topics, ORIGIN-in-comparison.
4. **Value sets** (`vsa.py`) — the constant half of the sink facts
   distilled into resolved CALL/DELEGATECALL targets (ROADMAP item
   4's cross-contract facts), constant storage slots, and the
   UserAssertions marker/topic evidence.
5. **Detector pre-screen** (`screen.py`) — per-module opcode/feature
   signatures over the reachable instruction set PLUS semantic sink
   predicates over the taint/value-set facts, so
   `analysis/security.py` loads only modules that can possibly fire
   on this contract. When every module screens off the contract is
   `static_answerable`: the static-answer triage tier settles it
   with an empty issue set at service admission / corpus dispatch.
6. **Prune feed** (`summary.py` StaticSummary) — consumed by
   `laser/batch/seeds.py` (dispatcher seeds for statically-inert
   functions are dropped) and `laser/batch/explore.py` (dead branch
   directions never enter the flip frontier); also exports
   per-selector function fingerprints (item 3's incremental
   re-analysis key) and the taint lint checks behind
   `myth lint --fail-on`.

The whole pass is pure host work (no jax, no device): `myth lint`
runs it standalone, `myth analyze`/`myth serve` run it as an always-on
prepass, and the service engine caches summaries by code hash in its
existing LRU beside the dense disassembly rows.

Manticore (arxiv 1907.03890) fronts symbolic exploration with exactly
this kind of CFG recovery; the Blockchain Superoptimizer (arxiv
2005.05912) shows how far pure constant propagation over EVM stack
code reaches without a solver — this layer is the batched-arena
adaptation of both.
"""

from __future__ import annotations

from mythril_tpu.analysis.static.callgraph import (
    LINK_CHECKS,
    PROXY_IMPL_SLOTS,
    PROXY_SLOTS,
    UPGRADE_SELECTORS,
    ContractNode,
    implementation_from_init_code,
    link_node,
    link_stat_counts,
    minimal_proxy_target,
)
from mythril_tpu.analysis.static.cfg import BasicBlock, recover_cfg
from mythril_tpu.analysis.static.linkset import (
    GRAPH_SCHEMA_VERSION,
    LinkSet,
    link_corpus,
)
from mythril_tpu.analysis.static.screen import (
    MODULE_SIGNATURES,
    SINK_PREDICATES,
    screen_modules,
)
from mythril_tpu.analysis.static.summary import (
    LINT_CHECKS,
    LINT_SCHEMA_VERSION,
    StaticSummary,
    analysis_config_fingerprint,
    analyze_bytecode,
    clear_static_cache,
    static_cache_stats,
    summary_for,
)
from mythril_tpu.analysis.static.taint import (
    TAINT_ATTACKER,
    TAINT_CALLER,
    TAINT_ORIGIN,
    TAINT_UNKNOWN,
    TaintResult,
    run_taint,
)
from mythril_tpu.analysis.static.vsa import ValueSets, value_sets


def static_prune_enabled() -> bool:
    """One switch for every consumer (CLI --no-static-prune)."""
    from mythril_tpu.support.support_args import args

    return bool(getattr(args, "static_prune", True))


def static_answer_enabled() -> bool:
    """The static-answer triage tier's switch: rides the static-prune
    flag (off under --no-static-prune — full-mount parity) plus its
    own `args.static_answer` knob (the test conftest turns the tier
    off so wave/walk-mechanics suites keep their subject; the product
    default is on)."""
    from mythril_tpu.support.support_args import args

    return static_prune_enabled() and bool(
        getattr(args, "static_answer", True)
    )


__all__ = [
    "BasicBlock",
    "ContractNode",
    "GRAPH_SCHEMA_VERSION",
    "LINK_CHECKS",
    "LINT_CHECKS",
    "LINT_SCHEMA_VERSION",
    "LinkSet",
    "PROXY_IMPL_SLOTS",
    "PROXY_SLOTS",
    "UPGRADE_SELECTORS",
    "MODULE_SIGNATURES",
    "SINK_PREDICATES",
    "StaticSummary",
    "TAINT_ATTACKER",
    "TAINT_CALLER",
    "TAINT_ORIGIN",
    "TAINT_UNKNOWN",
    "TaintResult",
    "ValueSets",
    "analysis_config_fingerprint",
    "analyze_bytecode",
    "clear_static_cache",
    "implementation_from_init_code",
    "link_corpus",
    "link_node",
    "link_stat_counts",
    "minimal_proxy_target",
    "recover_cfg",
    "run_taint",
    "screen_modules",
    "static_answer_enabled",
    "static_cache_stats",
    "static_prune_enabled",
    "summary_for",
    "value_sets",
]
