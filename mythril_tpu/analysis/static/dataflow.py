"""Abstract stack-height + constant-lattice dataflow over the CFG.

A worklist fixpoint over basic blocks. The abstract state per block
entry is (stack-height interval, top-window of abstract values); the
value lattice is {constant int} < TOP (None). The pass:

- resolves computed jumps whose target is a stack constant at the
  JUMP (the peephole in cfg.py only sees `PUSH t; JUMP`; this one
  sees the target through DUP/SWAP/POP shuffles and arithmetic on
  constants — the superoptimizer-style constant propagation of arxiv
  2005.05912, §3, restricted to what seeding needs);
- constant-folds JUMPI conditions: a condition that is the same
  constant on EVERY path into the branch makes the contradicted
  direction statically dead;
- flags blocks that DEFINITELY underflow the stack (reverting on all
  paths) and const jumps to invalid destinations;
- computes the reachable block set conservatively: an unresolved
  (still-TOP) jump target is treated as "any JUMPDEST", so
  reachability over-approximates and everything derived from it
  (detector screen, dead-code accounting) stays sound.

Termination: the value lattice is finite per slot, window length only
shrinks, and height intervals only widen within [0, 1024]; a visit
cap backstops pathological graphs — hitting it marks the result
`incomplete` and every consumer falls back to the conservative
whole-stream view.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Set, Tuple

from mythril_tpu.analysis.static.cfg import CFG, BasicBlock, stack_effect

log = logging.getLogger(__name__)

TOP = None
WORD = 2**256
MASK = WORD - 1
#: EVM stack limit — the height interval's natural ceiling
STACK_LIMIT = 1024
#: modeled stack window (top slots); values below are TOP
DEPTH_CAP = 32
#: worklist visit backstop
VISIT_CAP = 60_000


class AbsState:
    """Abstract machine state at a block boundary."""

    __slots__ = ("lo", "hi", "stack")

    def __init__(self, lo: int, hi: int, stack: Tuple) -> None:
        self.lo = lo
        self.hi = hi
        self.stack = stack  # top at index -1; len <= DEPTH_CAP

    def key(self) -> Tuple:
        return (self.lo, self.hi, self.stack)

    @staticmethod
    def unknown() -> "AbsState":
        return AbsState(0, STACK_LIMIT, ())


def join(a: Optional[AbsState], b: AbsState) -> AbsState:
    if a is None:
        return b
    n = min(len(a.stack), len(b.stack))
    if n:
        merged = tuple(
            x if x == y else TOP
            for x, y in zip(a.stack[-n:], b.stack[-n:])
        )
    else:
        merged = ()
    return AbsState(min(a.lo, b.lo), max(a.hi, b.hi), merged)


def _fold(op: str, a, b):
    """Constant fold a binary op; operand `a` is the stack top."""
    if a is TOP or b is TOP:
        return TOP
    try:
        if op == "ADD":
            return (a + b) & MASK
        if op == "SUB":
            return (a - b) & MASK
        if op == "MUL":
            return (a * b) & MASK
        if op == "DIV":
            return (a // b) & MASK if b else 0
        if op == "MOD":
            return (a % b) & MASK if b else 0
        if op == "EXP":
            return pow(a, b, WORD)
        if op == "AND":
            return a & b
        if op == "OR":
            return a | b
        if op == "XOR":
            return a ^ b
        if op == "EQ":
            return int(a == b)
        if op == "LT":
            return int(a < b)
        if op == "GT":
            return int(a > b)
        if op == "SHL":
            return (b << a) & MASK if a < 256 else 0
        if op == "SHR":
            return (b >> a) if a < 256 else 0
        if op == "BYTE":
            return (b >> (8 * (31 - a))) & 0xFF if a < 32 else 0
    except (OverflowError, ValueError):  # pragma: no cover
        return TOP
    return TOP


_BINARY = frozenset(
    [
        "ADD", "SUB", "MUL", "DIV", "MOD", "EXP", "AND", "OR", "XOR",
        "EQ", "LT", "GT", "SHL", "SHR", "BYTE",
    ]
)


class BlockFacts:
    """What one transfer of a block established (final pass only)."""

    __slots__ = (
        "jump_target",
        "jump_unresolved",
        "invalid_jump",
        "dead_direction",
        "definite_underflow",
        "possible_underflow",
    )

    def __init__(self) -> None:
        self.jump_target: Optional[int] = None
        self.jump_unresolved = False
        self.invalid_jump = False
        #: True/False = the JUMPI direction proven infeasible here
        self.dead_direction: Optional[bool] = None
        self.definite_underflow = False
        self.possible_underflow = False


def transfer(
    block: BasicBlock, state: AbsState
) -> Tuple[AbsState, BlockFacts]:
    """Run the abstract interpreter over one block from `state`."""
    lo, hi = state.lo, state.hi
    stack: List = list(state.stack)
    facts = BlockFacts()

    def pop():
        nonlocal lo, hi
        value = stack.pop() if stack else TOP
        lo, hi = max(0, lo - 1), max(0, hi - 1)
        return value

    def push(value) -> None:
        nonlocal lo, hi
        stack.append(value)
        lo, hi = min(STACK_LIMIT, lo + 1), min(STACK_LIMIT, hi + 1)
        if len(stack) > DEPTH_CAP:
            del stack[0]

    for ins in block.instructions:
        op = ins.opcode
        pops, pushes = stack_effect(op)
        if pops:
            if hi < pops:
                # every path into this instruction underflows: the
                # block reverts before doing anything further
                facts.definite_underflow = True
                break
            if lo < pops:
                facts.possible_underflow = True
        if op.startswith("PUSH"):
            push(int(ins.argument, 16) if ins.argument else 0)
        elif op.startswith("DUP"):
            n = int(op[3:])
            value = stack[-n] if len(stack) >= n else TOP
            push(value)
        elif op.startswith("SWAP"):
            n = int(op[4:])
            if len(stack) >= n + 1:
                stack[-1], stack[-n - 1] = stack[-n - 1], stack[-1]
            else:
                # the swapped-with slot is below the window: the top
                # becomes unknown, the deep slot is already TOP
                if stack:
                    stack[-1] = TOP
        elif op == "POP":
            pop()
        elif op in _BINARY:
            a, b = pop(), pop()
            push(_fold(op, a, b))
        elif op == "ISZERO":
            a = pop()
            push(TOP if a is TOP else int(a == 0))
        elif op == "NOT":
            a = pop()
            push(TOP if a is TOP else (~a) & MASK)
        elif op == "JUMP":
            target = pop()
            if target is TOP:
                facts.jump_unresolved = True
            else:
                facts.jump_target = int(target)
        elif op == "JUMPI":
            target = pop()
            cond = pop()
            if target is TOP:
                facts.jump_unresolved = True
            else:
                facts.jump_target = int(target)
            if cond is not TOP:
                # the contradicted direction can never execute;
                # True means "the taken direction is dead" (cond == 0)
                facts.dead_direction = not bool(cond)
        else:
            for _ in range(pops):
                pop()
            for _ in range(pushes):
                push(TOP)
    return AbsState(lo, hi, tuple(stack)), facts


class DataflowResult:
    """Fixpoint output consumed by summary.py."""

    def __init__(self) -> None:
        self.entry_states: Dict[int, AbsState] = {}
        self.reachable: Set[int] = set()
        self.resolved_jumps: Dict[int, int] = {}  # jump pc -> target pc
        self.unresolved_jumps: Set[int] = set()  # jump pc
        self.invalid_jumps: Dict[int, int] = {}  # jump pc -> bad target
        self.dead_directions: Set[Tuple[int, bool]] = set()
        self.underflow_blocks: Set[int] = set()
        self.possible_underflow_blocks: Set[int] = set()
        self.incomplete = False


def _successors(
    cfg: CFG, block: BasicBlock, facts: BlockFacts
) -> Tuple[List[int], bool]:
    """(successor block starts, broadcast-to-all-jumpdests?)."""
    out: List[int] = []
    terminator = block.terminator
    if facts.definite_underflow:
        return out, False
    if terminator == "JUMP":
        if facts.jump_unresolved:
            return out, True
        if facts.jump_target in cfg.jumpdests:
            out.append(facts.jump_target)
        return out, False
    if terminator == "JUMPI":
        broadcast = False
        if facts.dead_direction is not True:  # taken side feasible
            if facts.jump_unresolved:
                broadcast = True
            elif facts.jump_target in cfg.jumpdests:
                out.append(facts.jump_target)
        if facts.dead_direction is not False:  # fall side feasible
            nxt = cfg.block_after(block.start)
            if nxt is not None:
                out.append(nxt.start)
        return out, broadcast
    if terminator == "FALL":
        nxt = cfg.block_after(block.start)
        if nxt is not None:
            out.append(nxt.start)
    return out, False


def run_dataflow(cfg: CFG) -> DataflowResult:
    """Worklist fixpoint + a recording pass over the final states."""
    result = DataflowResult()
    if not cfg.blocks:
        return result

    entry = cfg.starts[0]
    in_states: Dict[int, AbsState] = {entry: AbsState(0, 0, ())}
    work: List[int] = [entry]
    jumpdest_starts = [s for s in cfg.starts if cfg.blocks[s].is_jumpdest]
    broadcast_done = False
    visits = 0
    while work:
        visits += 1
        if visits > VISIT_CAP:
            result.incomplete = True
            log.debug(
                "static dataflow visit cap hit (%d blocks); conservative "
                "fallback",
                len(cfg.blocks),
            )
            break
        start = work.pop()
        state = in_states[start]
        out_state, facts = transfer(cfg.blocks[start], state)
        successors, broadcast = _successors(cfg, cfg.blocks[start], facts)
        targets = list(successors)
        if broadcast and not broadcast_done:
            # one unresolved jump makes every JUMPDEST conservatively
            # reachable with an unknown state; doing this once is
            # enough — the unknown state joins everything to itself
            broadcast_done = True
            unknown = AbsState.unknown()
            for s in jumpdest_starts:
                merged = join(in_states.get(s), unknown)
                if s not in in_states or merged.key() != in_states[s].key():
                    in_states[s] = merged
                    work.append(s)
        for s in targets:
            if s not in cfg.blocks:
                continue
            merged = join(in_states.get(s), out_state)
            if s not in in_states or merged.key() != in_states[s].key():
                in_states[s] = merged
                work.append(s)

    result.entry_states = in_states
    result.reachable = set(in_states)
    if result.incomplete:
        # conservative: everything is reachable, nothing is dead
        result.reachable = set(cfg.blocks)
        return result

    # recording pass: facts are only trusted at the FIXPOINT states —
    # a dead direction observed mid-iteration could be an artifact of
    # a not-yet-joined path
    for start, state in in_states.items():
        block = cfg.blocks[start]
        _, facts = transfer(block, state)
        if facts.definite_underflow:
            result.underflow_blocks.add(start)
        if facts.possible_underflow:
            result.possible_underflow_blocks.add(start)
        if block.terminator in ("JUMP", "JUMPI"):
            pc = block.end
            if facts.jump_unresolved:
                result.unresolved_jumps.add(pc)
            elif facts.jump_target is not None:
                if facts.jump_target in cfg.jumpdests:
                    result.resolved_jumps[pc] = facts.jump_target
                else:
                    result.invalid_jumps[pc] = facts.jump_target
            if block.terminator == "JUMPI" and facts.dead_direction is not None:
                result.dead_directions.add((pc, facts.dead_direction))
    return result
