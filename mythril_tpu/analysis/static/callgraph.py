"""Per-contract cross-contract call facts: the link half of the
static layer.

Where `vsa.py` distills "which call targets are constant", this module
types EVERY outbound call site of one contract for the corpus linker
(`linkset.py`): kind (CALL/DELEGATECALL/STATICCALL/CALLCODE plus the
CREATE family), owning selector (dispatcher span attribution), the
caller's taint on the target/value/gas operands, and a **target
provenance** class from a fixed ladder:

- ``minimal-proxy`` — the whole runtime is the EIP-1167 forwarder;
  the callee address is in the code bytes themselves;
- ``constant`` — the VSA-resolved constant target also appears as a
  PUSH20 immediate (a hardcoded address literal);
- ``constructor-immutable`` — constant at the fixpoint but NOT a
  PUSH20 literal (folded/masked constants, Solidity immutables);
- ``proxy-slot`` — non-constant, not attacker-steered, and the
  contract reads a recognized implementation slot (EIP-1967 /
  OpenZeppelin zeppelinos / Gnosis masterCopy) before the site;
- ``storage-slot`` — non-constant, not attacker-steered, some other
  constant storage slot is read (a registry-held address);
- ``tainted`` — the target carries the ATTACKER bit;
- ``unresolved`` — everything else.

The ladder over-approximates downward: a site classified
``proxy-slot`` may in truth read an unrelated slot (the per-site
taint mask cannot name WHICH slot fed the target) — consumers that
need certainty (the linked-fingerprint planner) treat only edges the
LinkSet actually bound to a callee codehash as resolved.

Proxy-slot **bindings** come from the same runtime code: a constant
SSTORE of a constant value into a recognized proxy slot binds that
slot to an implementation address (the "reset/upgrade to the baked-in
implementation" shape). Deployment-time bindings ride in through
`implementation_from_init_code` — the one scanner `chainstream/
watcher.py` shares so the streaming proxy-upgrade detector and the
linker can never drift on slot constants.

Everything here is pure host work over facts `StaticSummary` already
computed — no jax, no solver — so `myth lint` / `myth graph` keep
their sub-second budget.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Set, Tuple

from mythril_tpu.analysis.static.taint import (
    TAINT_ATTACKER,
    TAINT_UNKNOWN,
)

log = logging.getLogger(__name__)

# -- shared proxy constants (the watcher reuses these verbatim) -------------
#: keccak256("eip1967.proxy.implementation") - 1
EIP1967_IMPL_SLOT = int(
    "360894a13ba1a3210667c828492db98dca3e2076cc3735a920a3ca505d382bbc", 16
)
#: keccak256("eip1967.proxy.beacon") - 1
EIP1967_BEACON_SLOT = int(
    "a3f0ad74e5423aebfd80d3ef4346578335a9a72aeaee59ff6cb3582b35133d50", 16
)
#: keccak256("eip1967.proxy.admin") - 1
EIP1967_ADMIN_SLOT = int(
    "b53127684a568b3173ae13b9f8a6016e243e63b6e8ee1178d6a717850b5d6103", 16
)
#: keccak256("org.zeppelinos.proxy.implementation")
OZ_IMPL_SLOT = int(
    "7050c9e0f4ca769c69bd3a8ef740bc37934f8e2c036e5a723fd8ee048ed3f8c3", 16
)
#: Gnosis Safe masterCopy — storage slot 0 (only meaningful when a
#: DELEGATECALL reads it; slot 0 alone is far too common to name)
GNOSIS_MASTERCOPY_SLOT = 0

#: slot -> human name, the IMPLEMENTATION-bearing slots (admin/beacon
#: slots are recognized for classification but never hold callee code)
PROXY_IMPL_SLOTS: Dict[int, str] = {
    EIP1967_IMPL_SLOT: "eip1967.implementation",
    OZ_IMPL_SLOT: "zeppelinos.implementation",
}
PROXY_SLOTS: Dict[int, str] = dict(PROXY_IMPL_SLOTS)
PROXY_SLOTS[EIP1967_BEACON_SLOT] = "eip1967.beacon"
PROXY_SLOTS[EIP1967_ADMIN_SLOT] = "eip1967.admin"

#: upgradeTo(address) / upgradeToAndCall(address,bytes) — the
#: transparent-proxy admin surface the watcher matches on calldata
UPGRADE_SELECTORS: Dict[str, str] = {
    "0x3659cfe6": "upgradeTo",
    "0x4f1ef286": "upgradeToAndCall",
}

#: EIP-1167 minimal proxy runtime: prefix + 20 address bytes + suffix
MINIMAL_PROXY_PREFIX = bytes.fromhex("363d3d373d3d3d363d73")
MINIMAL_PROXY_SUFFIX = bytes.fromhex("5af43d82803e903d91602b57fd5bf3")
#: pc of the DELEGATECALL (0xf4) inside the 45-byte runtime
MINIMAL_PROXY_CALL_PC = len(MINIMAL_PROXY_PREFIX) + 20 + 1

ADDRESS_MASK = (1 << 160) - 1

# -- provenance ladder ------------------------------------------------------
PROV_MINIMAL_PROXY = "minimal-proxy"
PROV_CONSTANT = "constant"
PROV_IMMUTABLE = "constructor-immutable"
PROV_PROXY_SLOT = "proxy-slot"
PROV_STORAGE_SLOT = "storage-slot"
PROV_TAINTED = "tainted"
PROV_UNRESOLVED = "unresolved"

#: provenances whose target ADDRESS is statically known or slot-bound
ADDRESSABLE_PROVENANCE = frozenset(
    [PROV_MINIMAL_PROXY, PROV_CONSTANT, PROV_IMMUTABLE, PROV_PROXY_SLOT]
)

#: the cross-contract lint checks this layer adds (summary.py folds
#: them into LINT_CHECKS; `proxy-storage-collision` needs the pair and
#: fires from LinkSet findings, the rest are single-contract)
LINK_CHECKS = frozenset(
    [
        "delegatecall-to-upgradeable-target",
        "proxy-storage-collision",
        "tainted-cross-contract-call-arg",
        "untrusted-return-data-in-guard",
    ]
)

_CALL_KINDS = ("CALL", "CALLCODE", "DELEGATECALL", "STATICCALL")
_CREATE_KINDS = ("CREATE", "CREATE2")


def minimal_proxy_target(code: bytes) -> Optional[int]:
    """The implementation address when `code` is exactly the EIP-1167
    minimal-proxy runtime, else None."""
    if (
        len(code)
        == len(MINIMAL_PROXY_PREFIX) + 20 + len(MINIMAL_PROXY_SUFFIX)
        and code.startswith(MINIMAL_PROXY_PREFIX)
        and code.endswith(MINIMAL_PROXY_SUFFIX)
    ):
        return int.from_bytes(
            code[len(MINIMAL_PROXY_PREFIX) : len(MINIMAL_PROXY_PREFIX) + 20],
            "big",
        )
    return None


def _push_sweep(code: bytes):
    """(pc, width, immediate int) for every PUSH in a linear sweep."""
    pc = 0
    n = len(code)
    while pc < n:
        op = code[pc]
        if 0x60 <= op <= 0x7F:
            width = op - 0x60 + 1
            arg = code[pc + 1 : pc + 1 + width]
            yield pc, width, int.from_bytes(arg, "big")
            pc += 1 + width
        else:
            pc += 1


def implementation_from_init_code(init_code) -> Optional[int]:
    """The initial implementation address a deployment's init code
    stores into a NAMED proxy slot (EIP-1967 / zeppelinos): the
    ``PUSH20 impl; PUSH32 slot; SSTORE`` constructor shape, linear
    sweep, no CFG. This is the detector `chainstream/watcher.py` layers
    beside its upgradeTo-selector match — both read the slot constants
    above, so the two detectors cannot drift. Slot 0 (Gnosis) is
    deliberately NOT matched here: an SSTORE to slot 0 in init code is
    far too common to call a proxy wiring."""
    if isinstance(init_code, str):
        init_code = init_code[2:] if init_code.startswith("0x") else init_code
        try:
            init_code = bytes.fromhex(init_code)
        except ValueError:
            return None
    if not init_code:
        return None
    last_addr: Optional[int] = None
    pending_slot = False
    for pc, width, arg in _push_sweep(init_code):
        if width == 20:
            last_addr = arg
            pending_slot = False
        elif width == 32 and arg in PROXY_IMPL_SLOTS:
            pending_slot = True
        elif pending_slot and last_addr is not None:
            # any op between the slot push and SSTORE other than the
            # address push resets nothing — the sweep only needs the
            # slot push to FOLLOW the address push (constructor shape)
            return last_addr & ADDRESS_MASK
    if pending_slot and last_addr is not None:
        # slot push was the last push before the (non-push) SSTORE tail
        return last_addr & ADDRESS_MASK
    return None


class CallSite:
    """One typed outbound call/create site of one contract."""

    __slots__ = (
        "pc",
        "kind",
        "provenance",
        "target_address",
        "slot",
        "target_taint",
        "value_taint",
        "gas_taint",
        "args_attacker",
        "selector",
    )

    def __init__(
        self,
        pc: int,
        kind: str,
        provenance: str,
        target_address: Optional[int] = None,
        slot: Optional[int] = None,
        target_taint: int = 0,
        value_taint: int = 0,
        gas_taint: int = 0,
        args_attacker: bool = False,
        selector: Optional[str] = None,
    ) -> None:
        self.pc = pc
        self.kind = kind
        self.provenance = provenance
        self.target_address = target_address
        self.slot = slot
        self.target_taint = target_taint
        self.value_taint = value_taint
        self.gas_taint = gas_taint
        #: the call's input memory carries attacker bytes (calldata was
        #: copied into memory somewhere in the contract — the global
        #: memory join's documented over-approximation, refined to the
        #: CALLDATACOPY/RETURNDATACOPY feature so a contract that never
        #: copies calldata stays clean)
        self.args_attacker = args_attacker
        self.selector = selector

    def as_dict(self) -> Dict:
        out: Dict = {
            "pc": self.pc,
            "kind": self.kind,
            "provenance": self.provenance,
            "selector": self.selector,
            "target_taint": self.target_taint,
            "args_attacker": self.args_attacker,
        }
        if self.target_address is not None:
            out["target_address"] = f"0x{self.target_address:040x}"
        if self.slot is not None:
            out["slot"] = hex(self.slot)
        return out


class ContractNode:
    """One contract's link-relevant facts: typed call sites, proxy
    classification, slot bindings, and the escape-summary inputs."""

    __slots__ = (
        "code_hash",
        "code_len",
        "call_sites",
        "selectors",
        "slot_bindings",
        "proxy_kind",
        "proxy_slots_read",
        "proxy_slots_written",
        "upgrade_selectors",
        "storage_reads",
        "storage_writes",
        "guard_return_pcs",
        "minimal_proxy",
        "incomplete",
    )

    def __init__(self, code_hash: str, code_len: int) -> None:
        self.code_hash = code_hash
        self.code_len = code_len
        self.call_sites: List[CallSite] = []
        #: selector hex -> entry pc (from the dispatcher recovery)
        self.selectors: Dict[str, int] = {}
        #: proxy slot -> baked-in implementation address (constant
        #: SSTOREs of constant values into named slots)
        self.slot_bindings: Dict[int, int] = {}
        self.proxy_kind: Optional[str] = None
        self.proxy_slots_read: List[int] = []
        self.proxy_slots_written: List[int] = []
        #: upgradeTo/upgradeToAndCall selectors this dispatcher mounts
        self.upgrade_selectors: List[str] = []
        self.storage_reads: Set[int] = set()
        self.storage_writes: Set[int] = set()
        #: JUMPI pcs whose guard condition carries the memory join's
        #: ATTACKER+UNKNOWN signature after a call site (return data
        #: steering control flow — see `untrusted-return-data-in-guard`)
        self.guard_return_pcs: List[int] = []
        self.minimal_proxy = False
        #: taint fixpoint unavailable: sites may be missing — the
        #: linker must treat this node's closure as unresolved
        self.incomplete = False

    # -- derived views ---------------------------------------------------
    @property
    def is_proxy(self) -> bool:
        return self.proxy_kind is not None

    @property
    def upgradeable(self) -> bool:
        """Can the implementation binding move after deployment?"""
        return bool(self.upgrade_selectors or self.proxy_slots_written)

    @property
    def out_degree(self) -> int:
        return len(self.call_sites)

    @property
    def delegatecall_sites(self) -> List[CallSite]:
        return [
            s
            for s in self.call_sites
            if s.kind in ("DELEGATECALL", "CALLCODE")
        ]

    def sites_in_selector(self, selector: str) -> List[CallSite]:
        return [s for s in self.call_sites if s.selector == selector]

    def provenance_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for site in self.call_sites:
            out[site.provenance] = out.get(site.provenance, 0) + 1
        return out

    def as_dict(self) -> Dict:
        return {
            "code_hash": self.code_hash,
            "code_len": self.code_len,
            "out_degree": self.out_degree,
            "call_sites": [s.as_dict() for s in self.call_sites],
            "delegatecall_sites": len(self.delegatecall_sites),
            "provenance": self.provenance_counts(),
            "is_proxy": self.is_proxy,
            "proxy_kind": self.proxy_kind,
            "upgradeable": self.upgradeable,
            "minimal_proxy": self.minimal_proxy,
            "slot_bindings": {
                hex(slot): f"0x{addr:040x}"
                for slot, addr in sorted(self.slot_bindings.items())
            },
            "incomplete": self.incomplete,
        }

    # -- single-contract link findings ----------------------------------
    def findings(self) -> List[Dict]:
        """The per-contract half of the LINK_CHECKS (the pair-level
        `proxy-storage-collision` fires from LinkSet.findings())."""
        out: List[Dict] = []
        upg = [
            s
            for s in self.delegatecall_sites
            if s.provenance == PROV_PROXY_SLOT and self.upgradeable
        ]
        if upg:
            out.append(
                {
                    "check": "delegatecall-to-upgradeable-target",
                    "detail": (
                        f"{len(upg)} DELEGATECALL(s) through a proxy "
                        "implementation slot that this contract can "
                        "rewrite (upgrade selector or direct slot "
                        "store) — the code behind the call can change "
                        "after any audit of it"
                    ),
                    "addresses": sorted(s.pc for s in upg)[:16],
                }
            )
        tainted_args = [
            s
            for s in self.call_sites
            if s.kind in _CALL_KINDS
            and s.args_attacker
            # tainted targets already fire tainted-delegatecall-target
            # territory; a minimal proxy forwards calldata BY DESIGN —
            # the callee, not the forwarder, is the finding's subject
            and s.provenance not in (PROV_TAINTED, PROV_MINIMAL_PROXY)
        ]
        if tainted_args:
            out.append(
                {
                    "check": "tainted-cross-contract-call-arg",
                    "detail": (
                        f"{len(tainted_args)} outbound call(s) whose "
                        "input memory carries attacker-controlled "
                        "calldata bytes — the callee executes on "
                        "attacker-shaped arguments"
                    ),
                    "addresses": sorted(s.pc for s in tainted_args)[:16],
                }
            )
        if self.guard_return_pcs:
            out.append(
                {
                    "check": "untrusted-return-data-in-guard",
                    "detail": (
                        f"{len(self.guard_return_pcs)} branch guard(s) "
                        "after an external call read memory the callee "
                        "may have written — control flow keyed on "
                        "unvalidated return data"
                    ),
                    "addresses": sorted(self.guard_return_pcs)[:16],
                }
            )
        return out


def _selector_for_pc(
    spans: Dict[str, List[Tuple[int, int]]], pc: int
) -> Optional[str]:
    owners = [
        sel
        for sel, rows in spans.items()
        if any(start <= pc <= end for start, end in rows)
    ]
    return owners[0] if len(owners) == 1 else None


def link_node(code: bytes, summary) -> ContractNode:
    """Build one contract's ContractNode from its StaticSummary (the
    taint/VSA facts are read, never recomputed)."""
    node = ContractNode(summary.code_hash, len(code))

    # whole-code EIP-1167 match first: the forwarder has no dispatcher
    # and needs no taint facts — the callee is in the bytes
    target = minimal_proxy_target(code)
    if target is not None:
        node.minimal_proxy = True
        node.proxy_kind = "eip1167"
        node.call_sites.append(
            CallSite(
                pc=MINIMAL_PROXY_CALL_PC,
                kind="DELEGATECALL",
                provenance=PROV_MINIMAL_PROXY,
                target_address=target,
                args_attacker=True,  # forwards the raw calldata
            )
        )
        _record_node(node)
        return node

    taint = getattr(summary, "taint", None)
    if taint is None or taint.incomplete:
        node.incomplete = True
        _record_node(node)
        return node

    spans = summary.selector_subgraphs()
    node.selectors = {
        "0x" + entry.selector.hex(): entry.entry_pc
        for entry in summary.dispatcher
    }
    node.upgrade_selectors = sorted(
        sel for sel in node.selectors if sel in UPGRADE_SELECTORS
    )
    node.storage_reads = set(summary.vsa.constant_storage_reads)
    node.storage_writes = set(summary.vsa.constant_storage_writes)

    push20 = {
        arg & ADDRESS_MASK
        for _pc, width, arg in _push_sweep(code)
        if width == 20
    }
    mem_attacker = bool(
        {"CALLDATACOPY", "RETURNDATACOPY"} & set(summary.features)
    )

    # named-slot reads, per pc (the proxy-slot rung's evidence)
    named_reads: Dict[int, int] = {}
    for pc, slot in taint.sload_slots.items():
        if slot[0] is not None and slot[0] in PROXY_SLOTS:
            named_reads[pc] = slot[0]
    slot0_read_pcs = [
        pc
        for pc, slot in taint.sload_slots.items()
        if slot[0] == GNOSIS_MASTERCOPY_SLOT
    ]

    # slot bindings: constant value stored into a named impl slot
    for pc, slot in taint.sstore_slots.items():
        if slot[0] is None:
            continue
        if slot[0] in PROXY_SLOTS:
            node.proxy_slots_written.append(slot[0])
        if slot[0] in PROXY_IMPL_SLOTS:
            value = taint.sstore_values.get(pc)
            if value is not None and value[0] is not None:
                node.slot_bindings[slot[0]] = value[0] & ADDRESS_MASK
    node.proxy_slots_written = sorted(set(node.proxy_slots_written))
    node.proxy_slots_read = sorted(
        {slot for slot in named_reads.values()}
    )

    # every constant-slot SLOAD, per pc (the storage-slot rung names
    # the nearest one before the site, same rule as the proxy rung)
    const_reads: Dict[int, int] = {
        pc: slot[0]
        for pc, slot in taint.sload_slots.items()
        if slot[0] is not None and slot[0] not in PROXY_SLOTS
    }
    other_const_reads = node.storage_reads - set(PROXY_SLOTS)

    for pc, site in sorted(taint.call_sites.items()):
        kind = site["kind"]
        tgt = site["target"]
        value = site.get("value")
        sel = _selector_for_pc(spans, pc)
        provenance = PROV_UNRESOLVED
        address: Optional[int] = None
        slot: Optional[int] = None
        if tgt[0] is not None:
            address = tgt[0] & ADDRESS_MASK
            provenance = (
                PROV_CONSTANT if address in push20 else PROV_IMMUTABLE
            )
        elif tgt[1] & TAINT_ATTACKER:
            provenance = PROV_TAINTED
        elif named_reads and any(p < pc for p in named_reads):
            provenance = PROV_PROXY_SLOT
            # the nearest named-slot read before the site names the slot
            slot = named_reads[
                max(p for p in named_reads if p < pc)
            ]
            address = node.slot_bindings.get(slot)
        elif (
            kind in ("DELEGATECALL", "CALLCODE")
            and slot0_read_pcs
            and any(p < pc for p in slot0_read_pcs)
        ):
            provenance = PROV_PROXY_SLOT
            slot = GNOSIS_MASTERCOPY_SLOT
        elif other_const_reads:
            provenance = PROV_STORAGE_SLOT
            before = [p for p in const_reads if p < pc]
            if before:
                slot = const_reads[max(before)]
        node.call_sites.append(
            CallSite(
                pc=pc,
                kind=kind,
                provenance=provenance,
                target_address=address,
                slot=slot,
                target_taint=tgt[1],
                value_taint=value[1] if value is not None else 0,
                gas_taint=site["gas"][1],
                args_attacker=mem_attacker,
                selector=sel,
            )
        )

    # CREATE/CREATE2 sites: the taint pass records no call-site row for
    # them (the created code is the operand, not an address), so they
    # come from the reachable instruction stream — always unresolved
    # (the child's codehash does not exist before the call runs)
    reachable = getattr(taint, "reachable", set())
    for start in reachable:
        block = summary.cfg.blocks.get(start)
        if block is None:
            continue
        for ins in block.instructions:
            if ins.opcode in _CREATE_KINDS:
                node.call_sites.append(
                    CallSite(
                        pc=ins.address,
                        kind=ins.opcode,
                        provenance=PROV_UNRESOLVED,
                        args_attacker=mem_attacker,
                        selector=_selector_for_pc(spans, ins.address),
                    )
                )
    node.call_sites.sort(key=lambda s: s.pc)

    # proxy classification from the DELEGATECALL sites' slots
    for site in node.delegatecall_sites:
        if site.provenance != PROV_PROXY_SLOT:
            continue
        if site.slot in (EIP1967_IMPL_SLOT, EIP1967_BEACON_SLOT):
            node.proxy_kind = "eip1967"
        elif site.slot == OZ_IMPL_SLOT:
            node.proxy_kind = node.proxy_kind or "zeppelinos"
        elif site.slot == GNOSIS_MASTERCOPY_SLOT:
            node.proxy_kind = node.proxy_kind or "gnosis"

    # return-data-in-guard: a JUMPI after the first call site whose
    # condition carries BOTH the ATTACKER and UNKNOWN bits — the
    # signature of a value read back through the memory join (a pure
    # calldata guard carries ATTACKER alone, a pure storage guard
    # UNKNOWN alone); documented over-approximation
    if taint.call_sites:
        first_call = min(taint.call_sites)
        node.guard_return_pcs = sorted(
            pc
            for pc, cond in taint.jumpi_conditions.items()
            if pc > first_call
            and cond[1] & TAINT_ATTACKER
            and cond[1] & TAINT_UNKNOWN
        )

    _record_node(node)
    return node


# ---------------------------------------------------------------------------
# /stats + registry counters (`static.link.*`, `mtpu_static_link_*`)
# ---------------------------------------------------------------------------
_COUNTS_LOCK = threading.Lock()
_COUNTS: Dict[str, int] = {
    "nodes": 0,
    "call_sites": 0,
    "resolved_sites": 0,
    "proxies": 0,
    "minimal_proxies": 0,
    "escape_widened": 0,
    "pairs": 0,
    "collisions": 0,
}


def _bump(key: str, n: int = 1) -> None:
    if not n:
        return
    with _COUNTS_LOCK:
        _COUNTS[key] = _COUNTS.get(key, 0) + n
    try:
        from mythril_tpu.observe.registry import registry

        registry().counter(
            f"mtpu_static_link_{key}_total",
            f"static linker {key.replace('_', ' ')}",
        ).inc(n)
    except Exception:
        pass  # telemetry must never sink the link pass


def _record_node(node: ContractNode) -> None:
    _bump("nodes")
    _bump("call_sites", len(node.call_sites))
    _bump(
        "resolved_sites",
        sum(
            1
            for s in node.call_sites
            if s.provenance in ADDRESSABLE_PROVENANCE
        ),
    )
    if node.is_proxy:
        _bump("proxies")
    if node.minimal_proxy:
        _bump("minimal_proxies")


def link_stat_counts() -> Dict[str, int]:
    """The `/stats` ``static.link.*`` block (process-lifetime)."""
    with _COUNTS_LOCK:
        return dict(_COUNTS)


def reset_link_counts() -> None:
    with _COUNTS_LOCK:
        for key in _COUNTS:
            _COUNTS[key] = 0
