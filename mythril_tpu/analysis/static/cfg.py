"""CFG recovery over raw runtime bytecode.

Basic-block formation from the disassembler's instruction list plus
the peephole (PUSH-const directly before JUMP/JUMPI) jump-target
resolution. Computed jumps the peephole cannot see are resolved by
the dataflow pass (`dataflow.py`) where the target is a stack
constant.

The linear sweep IS the canonical instruction alignment for the EVM:
JUMPDEST validity is defined by the same sweep (a 0x5b byte inside
PUSH data is not a valid destination), so blocks recovered here match
what both the host engine and the batched device interpreter will
execute.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from mythril_tpu.disassembler import asm
from mythril_tpu.support.opcodes import OPCODES

#: opcodes after which control never falls through
TERMINATORS = frozenset(
    ["STOP", "RETURN", "REVERT", "ASSERT_FAIL", "SUICIDE", "JUMP", "INVALID"]
)


class BasicBlock:
    """One basic block: a maximal straight-line instruction run."""

    __slots__ = ("start", "instructions", "is_jumpdest")

    def __init__(self, start: int, instructions: List[asm.EvmInstruction]):
        self.start = start
        self.instructions = instructions
        self.is_jumpdest = bool(
            instructions and instructions[0].opcode == "JUMPDEST"
        )

    @property
    def terminator(self) -> str:
        """Opcode ending the block, or "FALL" when the block ends only
        because the next instruction starts a new leader."""
        last = self.instructions[-1].opcode if self.instructions else "FALL"
        if last in TERMINATORS or last == "JUMPI":
            return last
        return "FALL"

    @property
    def end(self) -> int:
        """Address of the last instruction."""
        return self.instructions[-1].address if self.instructions else self.start

    def __len__(self) -> int:
        return len(self.instructions)

    def __repr__(self) -> str:
        return (
            f"<BasicBlock {self.start}..{self.end} "
            f"n={len(self.instructions)} end={self.terminator}>"
        )


class CFG:
    """Recovered control-flow graph: blocks keyed by start pc."""

    def __init__(
        self,
        instructions: List[asm.EvmInstruction],
        blocks: Dict[int, BasicBlock],
        jumpdests: frozenset,
    ) -> None:
        self.instructions = instructions
        self.blocks = blocks
        self.jumpdests = jumpdests
        self.starts = sorted(blocks)
        #: peephole-resolved jump targets, {jump_pc: target_pc}
        self.peephole_targets: Dict[int, int] = {}
        self._resolve_peephole()

    def block_after(self, start: int) -> Optional[BasicBlock]:
        """The fall-through successor block of the block at `start`."""
        import bisect

        i = bisect.bisect_right(self.starts, start)
        if i < len(self.starts):
            return self.blocks[self.starts[i]]
        return None

    def _resolve_peephole(self) -> None:
        for block in self.blocks.values():
            if block.terminator not in ("JUMP", "JUMPI"):
                continue
            if len(block.instructions) < 2:
                continue
            prev = block.instructions[-2]
            if prev.opcode.startswith("PUSH") and prev.argument:
                self.peephole_targets[block.end] = int(prev.argument, 16)

    def static_successors(self, block: BasicBlock) -> List[int]:
        """Successor block starts known WITHOUT dataflow: fall-through
        plus peephole-resolved jump targets that land on a JUMPDEST."""
        out: List[int] = []
        terminator = block.terminator
        if terminator in ("JUMP", "JUMPI"):
            target = self.peephole_targets.get(block.end)
            if target is not None and target in self.jumpdests:
                out.append(target)
        if terminator in ("FALL", "JUMPI"):
            nxt = self.block_after(block.start)
            if nxt is not None:
                out.append(nxt.start)
        return out


def recover_cfg(code: bytes) -> CFG:
    """Bytecode -> CFG: disassemble (trailing solc metadata stripped,
    truncated trailing PUSH zero-padded per EVM semantics — see
    asm.disassemble) and split at leaders."""
    instructions = asm.disassemble(code)
    jumpdests = frozenset(
        ins.address for ins in instructions if ins.opcode == "JUMPDEST"
    )
    leaders = {0}
    for i, ins in enumerate(instructions):
        if ins.opcode == "JUMPDEST":
            leaders.add(ins.address)
        if ins.opcode in TERMINATORS or ins.opcode == "JUMPI":
            if i + 1 < len(instructions):
                leaders.add(instructions[i + 1].address)

    blocks: Dict[int, BasicBlock] = {}
    current: List[asm.EvmInstruction] = []
    start = 0
    for ins in instructions:
        if ins.address in leaders and current:
            blocks[start] = BasicBlock(start, current)
            current = []
        if not current:
            start = ins.address
        current.append(ins)
    if current:
        blocks[start] = BasicBlock(start, current)
    return CFG(instructions, blocks, jumpdests)


def stack_effect(opcode: str) -> Tuple[int, int]:
    """(pops, pushes) for an opcode; unknown opcodes (INVALID aliases)
    touch nothing."""
    row = OPCODES.get(opcode)
    if row is None:
        return 0, 0
    return row[1], row[2]
