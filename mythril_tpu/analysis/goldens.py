"""Canonical report shapes for golden-file comparison.

The reference pins complete CLI reports against committed expected
files (tests/cmd_line_test.py:17-47, tests/testdata/outputs_expected/);
this module defines the equivalent canonical form here: the full issue
list with every stable field, volatile values (timings) stripped, and
transaction sequences reduced to their replay inputs.

Producers: tools/make_goldens.py (regeneration) and
tests/analysis/test_golden_reports.py (comparison).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Dict, List, Tuple

#: generous per-contract walk budget for golden runs: every fixture
#: that can converge does; the ones that cannot are still pinned at
#: the exact-issue-set level
GOLDEN_EXECUTION_TIMEOUT = 120

def _fixture_dir() -> Path:
    """Explicit override -> the vendored in-repo copy (self-contained
    suite) -> the reference checkout."""
    override = os.environ.get("MYTHRIL_REFERENCE_DIR")
    if override:
        return Path(override) / "tests" / "testdata" / "inputs"
    vendored = (
        Path(__file__).resolve().parents[2]
        / "tests" / "testdata" / "vendored" / "inputs"
    )
    if vendored.is_dir():
        return vendored
    return Path("/root/reference") / "tests" / "testdata" / "inputs"


GOLDEN_FIXTURES = _fixture_dir()


def golden_corpus_run() -> List[Tuple[str, Dict]]:
    """THE golden analysis: one pinned configuration shared by the
    generator (tools/make_goldens.py) and the comparison test, so the
    goldens are always checked under the settings they were made
    with. Returns [(fixture stem, result dict)] in fixture order."""
    from mythril_tpu.analysis.corpus import analyze_corpus
    from mythril_tpu.laser.smt.solver.solver import reset_blast_session
    from mythril_tpu.support.model import clear_cache

    # hermetic: get_model's memo is process-global and keyed on
    # hash-consed term ids, so analyses run earlier in the same
    # process (e.g. other test files with different budgets) would
    # otherwise answer this run's queries with verdicts cached under
    # THEIR budgets — the goldens must not depend on test order.
    # (SymExecWrapper resets the blast session per contract already;
    # the explicit reset here makes the hermetic intent self-contained
    # rather than an inherited side effect.)
    clear_cache()
    reset_blast_session()
    files = sorted(GOLDEN_FIXTURES.glob("*.sol.o"))
    contracts = [(f.read_text().strip(), "", f.stem) for f in files]
    # deterministic solving: goldens are byte-compared, so every
    # marathon verdict must be a pure function of the query — wall
    # budgets alone let machine load flip a borderline solve and
    # drift a minimized witness (observed: a tx calldata length
    # oscillating 37/48 run-to-run on one fixture). Threaded as a
    # parameter (scoped + restored per analysis inside the runner)
    # rather than toggled on the process-global Args around the run.
    results = analyze_corpus(
        contracts,
        transaction_count=2,
        execution_timeout=GOLDEN_EXECUTION_TIMEOUT,
        create_timeout=10,
        processes=1,
        use_device=False,
        deterministic_solving=True,
    )
    return [(f.stem, r) for f, r in zip(files, results)]


def canonical_issues(issues: List[Dict]) -> List[Dict]:
    """Issue dicts (Issue.as_dict shape) -> deterministic golden rows.

    Transaction sequences are pinned by their model-independent
    structure — step count, each step's selector and calldata length —
    not the free argument bytes: those are one satisfying assignment
    among many, and the CDCL search (unlike z3's deterministic tactics)
    picks different ones across processes. Everything else (addresses,
    swc ids, titles, severities, functions, full descriptions, gas
    bounds) is compared byte for byte."""
    rows = []
    for issue in issues:
        row = dict(issue)
        steps = ((row.pop("tx_sequence", None) or {}).get("steps")) or []
        row["tx_steps"] = [
            {
                "selector": (step.get("input") or "")[:10],
                "calldata_bytes": max(0, (len(step.get("input") or "0x") - 2) // 2),
            }
            for step in steps
        ]
        rows.append(row)
    rows.sort(key=lambda r: (r["address"], r["title"], str(r.get("function"))))
    return rows
