"""TPU dispatcher probe: batched concrete triage of a contract's entry
points.

One lane per recovered function selector (plus fuzz lanes), all
executed concretely in a single batched device pass. Per function the
probe reports halt status, storage writes, gas bounds and instruction
coverage (from the engine's executed-pc bitmap) — a fast first look at
a contract's surface before symbolic analysis, and the batch engine's
counterpart of the coverage plugin (SURVEY.md §2.4: pruners/coverage
as batch-lane masks).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from mythril_tpu.disassembler.disassembly import Disassembly
from mythril_tpu.laser.batch.run import run
from mythril_tpu.laser.batch.state import Status, make_batch, make_code_table
from mythril_tpu.ops import u256

_STATUS_NAMES = {
    Status.RUNNING: "running",
    Status.STOPPED: "stopped",
    Status.RETURNED: "returned",
    Status.REVERTED: "reverted",
    Status.INVALID: "invalid",
    Status.ERR_STACK: "stack-error",
    Status.ERR_JUMP: "jump-error",
    Status.ERR_MEM: "memory-cap",
    Status.UNSUPPORTED: "unsupported",
    Status.ERR_OOG: "out-of-gas",
}


def _coverage_percent(pc_seen_row: np.ndarray, n_instructions: int) -> float:
    if n_instructions == 0:
        return 0.0
    bits = np.unpackbits(
        pc_seen_row.view(np.uint8), bitorder="little"
    )
    return round(100.0 * int(bits.sum()) / n_instructions, 1)


def probe_dispatcher(
    code_hex: str,
    arg_words: int = 4,
    fuzz_lanes: int = 4,
    callvalue: int = 0,
    max_steps: int = 4096,
    seed: int = 1,
) -> List[Dict]:
    """Probe every recovered selector (plus empty-calldata and fuzz
    lanes) of runtime bytecode in one batched run."""
    disassembly = Disassembly(code_hex)
    code = bytes.fromhex(code_hex[2:] if code_hex.startswith("0x") else code_hex)
    rng = np.random.default_rng(seed)

    lanes: List[Dict] = []
    for func_hash in disassembly.func_hashes:
        selector = bytes.fromhex(func_hash[2:])
        try:
            from mythril_tpu.support.signatures import SignatureDB

            sigs = SignatureDB().get(func_hash)
            label = sigs[0] if sigs else func_hash
        except Exception:
            label = func_hash
        calldata = selector + rng.integers(
            0, 256, arg_words * 32, dtype=np.uint8
        ).tobytes()
        lanes.append({"label": label, "calldata": calldata})
    lanes.append({"label": "<empty calldata>", "calldata": b""})
    for k in range(fuzz_lanes):
        calldata = rng.integers(0, 256, 4 + arg_words * 32, dtype=np.uint8).tobytes()
        lanes.append({"label": f"<fuzz {k}>", "calldata": calldata})

    table = make_code_table([code])
    batch = make_batch(
        len(lanes),
        calldata=[lane["calldata"] for lane in lanes],
        callvalue=callvalue,
    )
    out, steps = run(batch, table, max_steps=max_steps)

    status = np.asarray(out.status)
    gas_min = np.asarray(out.gas_min)
    gas_max = np.asarray(out.gas_max)
    cnts = np.asarray(out.storage_cnt)
    keys = np.asarray(out.storage_keys)
    vals = np.asarray(out.storage_vals)
    pc_seen = np.asarray(out.pc_seen)
    n_instr = len(disassembly.instruction_list)

    results = []
    for i, lane in enumerate(lanes):
        writes = {}
        for k in range(int(cnts[i])):
            writes[hex(u256.to_int(keys[i, k]))] = hex(u256.to_int(vals[i, k]))
        results.append(
            {
                "function": lane["label"],
                "status": _STATUS_NAMES.get(int(status[i]), str(int(status[i]))),
                "gas": [int(gas_min[i]), int(gas_max[i])],
                "storage_writes": writes,
                "coverage_percent": _coverage_percent(pc_seen[i], n_instr),
            }
        )
    return results
