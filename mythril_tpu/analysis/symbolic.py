"""SymExecWrapper: configure and run LASER for one analysis.

Covers mythril/analysis/symbolic.py — strategy selection, the
bounded-loops extension, plugin loading, actor accounts, detection-
module hook registration, running `sym_exec`, and pre-digesting the
statespace's CALL operations for POST modules.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Set, Union

from mythril_tpu.analysis.module import (
    EntryPoint,
    ModuleLoader,
    get_detection_module_hooks,
)
from mythril_tpu.analysis.ops import Call, VarType, get_variable
from mythril_tpu.laser.ethereum import svm
from mythril_tpu.laser.ethereum.natives import PRECOMPILE_COUNT
from mythril_tpu.laser.ethereum.state.account import Account
from mythril_tpu.laser.ethereum.state.world_state import WorldState
from mythril_tpu.laser.ethereum.strategy.basic import (
    BreadthFirstSearchStrategy,
    DepthFirstSearchStrategy,
    ReturnRandomNaivelyStrategy,
    ReturnWeightedRandomStrategy,
)
from mythril_tpu.laser.ethereum.strategy.extensions.bounded_loops import (
    BoundedLoopsStrategy,
)
from mythril_tpu.laser.ethereum.transaction.symbolic import ACTORS
from mythril_tpu.laser.execution_info import ExecutionInfo
from mythril_tpu.laser.plugin.loader import LaserPluginLoader
from mythril_tpu.laser.plugin.plugins import (
    CallDepthLimitBuilder,
    CoveragePluginBuilder,
    DependencyPrunerBuilder,
    InstructionProfilerBuilder,
    MutationPrunerBuilder,
)
from mythril_tpu.laser.smt import BitVec, symbol_factory
from mythril_tpu.support.support_args import args

log = logging.getLogger(__name__)

STRATEGIES = {
    "dfs": DepthFirstSearchStrategy,
    "bfs": BreadthFirstSearchStrategy,
    "naive-random": ReturnRandomNaivelyStrategy,
    "weighted-random": ReturnWeightedRandomStrategy,
}

CALL_OPS = ("CALL", "CALLCODE", "DELEGATECALL", "STATICCALL")


class DeviceExplorationInfo(ExecutionInfo):
    """Device-prepass counters, surfaced in jsonv2 execution info."""

    def __init__(self, stats: dict) -> None:
        self.stats = stats

    def as_dict(self):
        return {"device_symbolic_prepass": self.stats}


class StaticAnalysisInfo(ExecutionInfo):
    """Static-prepass counters (analysis/static), surfaced in the
    jsonv2 report meta: CFG/prune stats plus the detector screen."""

    def __init__(self, stats: dict) -> None:
        self.stats = stats

    def as_dict(self):
        return {"static_analysis": self.stats}


def _as_address_term(address: Union[int, str, BitVec]) -> BitVec:
    if isinstance(address, str):
        address = int(address, 16)
    if isinstance(address, int):
        address = symbol_factory.BitVecVal(address, 256)
    return address


class SymExecWrapper:
    """Symbolically executes a contract and pre-digests the statespace
    for the analysis layer."""

    def __init__(
        self,
        contract,
        address: Union[int, str, BitVec],
        strategy: str,
        dynloader=None,
        max_depth: int = 22,
        execution_timeout: Optional[int] = None,
        loop_bound: int = 3,
        create_timeout: Optional[int] = None,
        transaction_count: int = 2,
        modules: Optional[List[str]] = None,
        compulsory_statespace: bool = True,
        disable_dependency_pruning: bool = False,
        run_analysis_modules: bool = True,
        custom_modules_directory: str = "",
        prepass_outcome: Optional[dict] = None,
    ):
        # fresh per-contract solver session: the blast store shares
        # structure within one analysis but would tax the next contract
        from mythril_tpu.analysis.prepass import reset_proven
        from mythril_tpu.laser.smt.solver.solver import reset_blast_session
        from mythril_tpu.support.phase_profile import PhaseProfile

        reset_blast_session()
        PhaseProfile().reset()
        reset_proven()  # device witnesses never outlive their contract

        if strategy not in STRATEGIES:
            raise ValueError("Invalid strategy argument supplied")
        address = _as_address_term(address)

        self.dynloader = dynloader
        deploys = bool(getattr(contract, "creation_code", None))

        requires_statespace = (
            compulsory_statespace
            or len(ModuleLoader().get_detection_modules(EntryPoint.POST, modules)) > 0
        )

        self.accounts = self._actor_accounts(include_creator=deploys)
        self.laser = svm.LaserEVM(
            dynamic_loader=dynloader,
            max_depth=max_depth,
            execution_timeout=execution_timeout,
            strategy=STRATEGIES[strategy],
            create_timeout=create_timeout,
            transaction_count=transaction_count,
            requires_statespace=requires_statespace,
        )
        if loop_bound is not None:
            self.laser.extend_strategy(BoundedLoopsStrategy, loop_bound)

        # the static prepass (analysis/static): CFG + dataflow once
        # per code hash, detector pre-screen BEFORE any hook mounts
        self.static_summary = None
        self.static_screen: Optional[Set[str]] = None
        self._static_prescreen(contract, deploys)

        self._mount_plugins(disable_dependency_pruning)
        if run_analysis_modules:
            self._mount_detectors(modules)

        world_state = WorldState()
        for account in self.accounts.values():
            world_state.put_account(account)

        self._injected_outcome = prepass_outcome
        self.device_exploration = self._device_prepass(
            contract, address, execution_timeout
        )

        if deploys:
            self.laser.sym_exec(
                creation_code=contract.creation_code,
                contract_name=contract.name,
                world_state=world_state,
            )
        else:
            world_state.put_account(
                self._target_account(contract, address, world_state)
            )
            self.laser.sym_exec(
                world_state=world_state, target_address=address.value
            )

        if requires_statespace:
            self.nodes = self.laser.nodes
            self.edges = self.laser.edges
            self.calls = list(self._digest_calls())

    # -- static prepass -------------------------------------------------
    def _static_prescreen(self, contract, deploys: bool) -> None:
        """Run the host-side static pass (cached by code hash) and
        derive the detector screen: modules whose opcode signature
        cannot fire on this code are never mounted and never run their
        POST pass (analysis/static/screen.py).

        Screening is skipped when on-chain loading is active — a
        DELEGATECALL into foreign code executes opcodes this
        contract's bytecode does not contain — and when the user
        passed --no-static-prune."""
        if not getattr(args, "static_prune", True):
            return
        if self.dynloader is not None and getattr(
            self.dynloader, "active", False
        ):
            return
        runtime = getattr(contract, "code", "") or ""
        if len(runtime) < 4:
            return
        try:
            from mythril_tpu.analysis.static import (
                screen_modules,
                summary_for,
            )

            self.static_summary = summary_for(runtime)
            if deploys:
                # creation code executes under the same hooks; its
                # linear sweep over-approximates (embedded runtime
                # decodes as instructions), which only ADDS features —
                # conservative in the right direction. The semantic
                # sink predicates only hold for the runtime body, so a
                # deploying analysis screens on opcodes alone.
                features = set(self.static_summary.features)
                features |= summary_for(
                    getattr(contract, "creation_code", "") or ""
                ).features
                applicable, skipped = screen_modules(features)
            else:
                # runtime-only: the semantic screen (opcode signature
                # AND the taint/value-set sink predicate) decides
                applicable, skipped = (
                    self.static_summary.applicable_modules()
                )
            self.static_screen = set(applicable)
            stats = self.static_summary.stats()
            stats["modules_skipped"] = sorted(skipped)
            self.laser.execution_info.append(StaticAnalysisInfo(stats))
            if skipped:
                log.info(
                    "Static pre-screen: %d/%d detection modules "
                    "applicable (skipped: %s)",
                    len(applicable),
                    len(applicable) + len(skipped),
                    ", ".join(sorted(skipped)),
                )
        except Exception:
            self.static_summary = None
            self.static_screen = None
            log.debug("static prescreen failed; all modules load",
                      exc_info=True)

    # -- device symbolic prepass ----------------------------------------
    def _device_prepass(self, contract, address: BitVec, execution_timeout):
        """Explore the contract's runtime code with the device
        symbolic engine before the host walk (arena + portfolio; see
        laser/batch/explore.py). Default "auto": runs when an
        accelerator backend is present.

        The prepass is not a warmup — its results drive the analysis:
        trigger witnesses become concrete Issues (analysis/prepass.py)
        and the covered branch-direction set lets the host walk skip
        per-fork feasibility queries the device already has a concrete
        execution for (svm.py)."""
        self.device_issues = []
        runtime = getattr(contract, "code", "") or ""
        if runtime.startswith("0x"):
            runtime = runtime[2:]

        outcome = self._injected_outcome
        if outcome is None:
            mode = getattr(args, "device_prepass", "auto")
            if mode == "never":
                return None
            if mode == "auto":
                from mythril_tpu.support.accel import accelerator_present

                if not accelerator_present():
                    return None

            if len(runtime) < 8:
                return None

            # scale to the hardware, bounded by wall clock: waves stop
            # at a coverage plateau or when the budget can't fit
            # another wave. Tiny analysis timeouts skip the prepass
            # outright — even a cache-warm wave would eat a meaningful
            # slice of them.
            budget = float(getattr(args, "device_prepass_budget", 12.0))
            if execution_timeout:
                if execution_timeout < 6:
                    return None
                budget = min(budget, execution_timeout / 3.0)
            lanes = int(getattr(args, "device_prepass_lanes", 128))
            try:
                from mythril_tpu.laser.batch.explore import (
                    DeviceSymbolicExplorer,
                    required_calldata_len,
                )

                explorer = DeviceSymbolicExplorer(
                    runtime,
                    calldata_len=required_calldata_len(runtime),
                    lanes=lanes,
                    waves=8,
                    steps_per_wave=512,
                    budget_s=budget,
                    address=address.value,
                    transaction_count=self.laser.transaction_count,
                    # with on-chain loading, foreign accounts may carry
                    # code — CALLs must hand off to the host engine
                    empty_world=not (
                        self.dynloader is not None
                        and getattr(self.dynloader, "active", False)
                    ),
                )
                outcome = explorer.run()
            except Exception as why:  # the host walk must never be blocked
                log.debug("device prepass failed: %s", why)
                return None

        from mythril_tpu.support.phase_profile import PhaseProfile

        stats = outcome["stats"]
        if self._injected_outcome is None:
            # an injected outcome's wall was paid once for the whole
            # corpus; only an in-line exploration bills this contract
            PhaseProfile().add("prepass", stats.get("wall_s", 0.0))
        try:
            from mythril_tpu.analysis.prepass import (
                register_proven,
                witness_issues,
            )

            self.device_issues = witness_issues(contract, outcome, address.value)
            # the host modules skip their concretization solve at
            # addresses the device already holds a witness for
            register_proven(self.device_issues, runtime)
        except Exception as why:
            log.debug("prepass witness conversion failed: %s", why)
        stats["witness_issues"] = len(self.device_issues)

        log.info(
            "Device symbolic prepass: %d device steps over %d waves in "
            "%.1fs, %d arena nodes, %d/%d flips feasible (%d sat on "
            "device), %d branch directions covered, %d witness issues",
            stats["device_steps"],
            stats["waves"],
            stats["wall_s"],
            stats["arena_nodes"],
            stats["forks_feasible"],
            stats["forks_tried"],
            stats["device_sat"],
            stats["branches_covered"],
            stats["witness_issues"],
        )
        self.laser.execution_info.append(DeviceExplorationInfo(stats))
        # hand the host walk the concretely-executed branch directions:
        # forks into this set skip their feasibility query (the device
        # holds a concrete witness for the direction)
        self.laser.seed_device_coverage(
            {tuple(b) for b in outcome["covered_branches"]}, runtime
        )
        return outcome

    # -- setup pieces --------------------------------------------------
    @staticmethod
    def _actor_accounts(include_creator: bool) -> dict:
        accounts = {
            hex(ACTORS.attacker.value): Account(
                hex(ACTORS.attacker.value),
                "",
                dynamic_loader=None,
                contract_name=None,
            )
        }
        if include_creator:
            accounts[hex(ACTORS.creator.value)] = Account(
                hex(ACTORS.creator.value),
                "",
                dynamic_loader=None,
                contract_name=None,
            )
        return accounts

    def _mount_plugins(self, disable_dependency_pruning: bool) -> None:
        loader = LaserPluginLoader()
        loader.load(CoveragePluginBuilder())
        loader.load(MutationPrunerBuilder())
        loader.load(CallDepthLimitBuilder())
        if args.iprof:
            loader.load(InstructionProfilerBuilder())
        loader.add_args("call-depth-limit", call_depth_limit=args.call_depth_limit)
        if not disable_dependency_pruning:
            loader.load(DependencyPrunerBuilder())
        loader.instrument_virtual_machine(self.laser, None)

    def _mount_detectors(self, modules: Optional[List[str]]) -> None:
        detectors = ModuleLoader().get_detection_modules(
            EntryPoint.CALLBACK, modules
        )
        if self.static_screen is not None:
            # the pre-screen: a module whose opcode signature cannot
            # fire on this code never mounts its hooks (the svm pays
            # hook dispatch per executed instruction)
            detectors = [
                d
                for d in detectors
                if type(d).__name__ in self.static_screen
            ]
        for phase in ("pre", "post"):
            self.laser.register_hooks(
                hook_type=phase,
                hook_dict=get_detection_module_hooks(detectors, hook_type=phase),
            )

    def _target_account(self, contract, address: BitVec, world_state) -> Account:
        loader = self.dynloader
        account = Account(
            address,
            contract.disassembly,
            dynamic_loader=loader,
            contract_name=contract.name,
            balances=world_state.balances,
            concrete_storage=bool(loader is not None and loader.active),
        )
        if loader is not None:
            try:
                account.set_balance(
                    loader.read_balance("{0:#0{1}x}".format(address.value, 42))
                )
            except Exception:
                pass  # balance stays symbolic
        return account

    # -- statespace digestion ------------------------------------------
    def _digest_calls(self):
        """Yield a `Call` record for every CALL-family state in the
        statespace (input to the POST analysis modules)."""
        for node in self.nodes.values():
            for state_index, state in enumerate(node.states):
                try:
                    op = state.get_current_instruction()["opcode"]
                except IndexError:
                    continue
                if op not in CALL_OPS:
                    continue
                stack = state.mstate.stack
                gas = get_variable(stack[-1])
                to = get_variable(stack[-2])

                if op in ("CALL", "CALLCODE"):
                    value = get_variable(stack[-3])
                    mem_start = get_variable(stack[-4])
                    mem_size = get_variable(stack[-5])
                    if to.type == VarType.CONCRETE and 0 < to.val <= PRECOMPILE_COUNT:
                        continue  # precompile call, not interesting
                    if (
                        mem_start.type == VarType.CONCRETE
                        and mem_size.type == VarType.CONCRETE
                    ):
                        payload = state.mstate.memory[
                            mem_start.val : mem_start.val + mem_size.val
                        ]
                        yield Call(
                            node, state, state_index, op, to, gas, value, payload
                        )
                    else:
                        yield Call(node, state, state_index, op, to, gas, value)
                else:
                    yield Call(node, state, state_index, op, to, gas)

    @property
    def execution_info(self) -> List[ExecutionInfo]:
        return self.laser.execution_info
