"""SymExecWrapper: configure and run LASER for analysis.

Reference parity: mythril/analysis/symbolic.py:39-307 — strategy
selection, bounded-loops extension, plugin loading, creator/attacker
accounts, detection-module hook registration, `sym_exec`, and the
post-run extraction of `Call` records for POST modules.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Type, Union

from mythril_tpu.analysis.module import (
    EntryPoint,
    ModuleLoader,
    get_detection_module_hooks,
)
from mythril_tpu.analysis.ops import Call, VarType, get_variable
from mythril_tpu.laser.ethereum import svm
from mythril_tpu.laser.ethereum.natives import PRECOMPILE_COUNT
from mythril_tpu.laser.ethereum.state.account import Account
from mythril_tpu.laser.ethereum.state.world_state import WorldState
from mythril_tpu.laser.ethereum.strategy.basic import (
    BasicSearchStrategy,
    BreadthFirstSearchStrategy,
    DepthFirstSearchStrategy,
    ReturnRandomNaivelyStrategy,
    ReturnWeightedRandomStrategy,
)
from mythril_tpu.laser.ethereum.strategy.extensions.bounded_loops import (
    BoundedLoopsStrategy,
)
from mythril_tpu.laser.ethereum.transaction.symbolic import ACTORS
from mythril_tpu.laser.execution_info import ExecutionInfo
from mythril_tpu.laser.plugin.loader import LaserPluginLoader
from mythril_tpu.laser.plugin.plugins import (
    CallDepthLimitBuilder,
    CoveragePluginBuilder,
    DependencyPrunerBuilder,
    InstructionProfilerBuilder,
    MutationPrunerBuilder,
)
from mythril_tpu.laser.smt import BitVec, symbol_factory
from mythril_tpu.support.support_args import args

log = logging.getLogger(__name__)


class SymExecWrapper:
    """Symbolically executes a contract and pre-digests the statespace
    for the analysis layer."""

    def __init__(
        self,
        contract,
        address: Union[int, str, BitVec],
        strategy: str,
        dynloader=None,
        max_depth: int = 22,
        execution_timeout: Optional[int] = None,
        loop_bound: int = 3,
        create_timeout: Optional[int] = None,
        transaction_count: int = 2,
        modules: Optional[List[str]] = None,
        compulsory_statespace: bool = True,
        disable_dependency_pruning: bool = False,
        run_analysis_modules: bool = True,
        custom_modules_directory: str = "",
    ):
        # fresh per-contract solver session: the blast store shares
        # structure within one analysis but would tax the next contract
        from mythril_tpu.laser.smt.solver.solver import reset_blast_session

        reset_blast_session()

        if isinstance(address, str):
            address = symbol_factory.BitVecVal(int(address, 16), 256)
        if isinstance(address, int):
            address = symbol_factory.BitVecVal(address, 256)

        if strategy == "dfs":
            s_strategy: Type[BasicSearchStrategy] = DepthFirstSearchStrategy
        elif strategy == "bfs":
            s_strategy = BreadthFirstSearchStrategy
        elif strategy == "naive-random":
            s_strategy = ReturnRandomNaivelyStrategy
        elif strategy == "weighted-random":
            s_strategy = ReturnWeightedRandomStrategy
        else:
            raise ValueError("Invalid strategy argument supplied")

        creator_account = Account(
            hex(ACTORS.creator.value), "", dynamic_loader=None, contract_name=None
        )
        attacker_account = Account(
            hex(ACTORS.attacker.value), "", dynamic_loader=None, contract_name=None
        )

        requires_statespace = (
            compulsory_statespace
            or len(ModuleLoader().get_detection_modules(EntryPoint.POST, modules)) > 0
        )
        has_creation_code = bool(getattr(contract, "creation_code", None))
        if not has_creation_code:
            self.accounts = {hex(ACTORS.attacker.value): attacker_account}
        else:
            self.accounts = {
                hex(ACTORS.creator.value): creator_account,
                hex(ACTORS.attacker.value): attacker_account,
            }

        self.laser = svm.LaserEVM(
            dynamic_loader=dynloader,
            max_depth=max_depth,
            execution_timeout=execution_timeout,
            strategy=s_strategy,
            create_timeout=create_timeout,
            transaction_count=transaction_count,
            requires_statespace=requires_statespace,
        )

        if loop_bound is not None:
            self.laser.extend_strategy(BoundedLoopsStrategy, loop_bound)

        plugin_loader = LaserPluginLoader()
        plugin_loader.load(CoveragePluginBuilder())
        plugin_loader.load(MutationPrunerBuilder())
        plugin_loader.load(CallDepthLimitBuilder())
        if args.iprof:
            plugin_loader.load(InstructionProfilerBuilder())
        plugin_loader.add_args(
            "call-depth-limit", call_depth_limit=args.call_depth_limit
        )
        if not disable_dependency_pruning:
            plugin_loader.load(DependencyPrunerBuilder())
        plugin_loader.instrument_virtual_machine(self.laser, None)

        world_state = WorldState()
        for account in self.accounts.values():
            world_state.put_account(account)

        if run_analysis_modules:
            analysis_modules = ModuleLoader().get_detection_modules(
                EntryPoint.CALLBACK, modules
            )
            self.laser.register_hooks(
                hook_type="pre",
                hook_dict=get_detection_module_hooks(
                    analysis_modules, hook_type="pre"
                ),
            )
            self.laser.register_hooks(
                hook_type="post",
                hook_dict=get_detection_module_hooks(
                    analysis_modules, hook_type="post"
                ),
            )

        if has_creation_code:
            self.laser.sym_exec(
                creation_code=contract.creation_code,
                contract_name=contract.name,
                world_state=world_state,
            )
        else:
            account = Account(
                address,
                contract.disassembly,
                dynamic_loader=dynloader,
                contract_name=contract.name,
                balances=world_state.balances,
                concrete_storage=True
                if (dynloader is not None and dynloader.active)
                else False,
            )
            if dynloader is not None:
                try:
                    _balance = dynloader.read_balance(
                        "{0:#0{1}x}".format(address.value, 42)
                    )
                    account.set_balance(_balance)
                except Exception:
                    pass  # balance stays symbolic
            world_state.put_account(account)
            self.laser.sym_exec(world_state=world_state, target_address=address.value)

        if not requires_statespace:
            return

        self.nodes = self.laser.nodes
        self.edges = self.laser.edges

        # pre-digest CALL-family operations for POST modules
        self.calls: List[Call] = []
        for key in self.nodes:
            state_index = 0
            for state in self.nodes[key].states:
                try:
                    instruction = state.get_current_instruction()
                except IndexError:
                    state_index += 1
                    continue
                op = instruction["opcode"]
                if op in ("CALL", "CALLCODE", "DELEGATECALL", "STATICCALL"):
                    stack = state.mstate.stack
                    if op in ("CALL", "CALLCODE"):
                        gas, to, value, meminstart, meminsz = (
                            get_variable(stack[-1]),
                            get_variable(stack[-2]),
                            get_variable(stack[-3]),
                            get_variable(stack[-4]),
                            get_variable(stack[-5]),
                        )
                        if (
                            to.type == VarType.CONCRETE
                            and 0 < to.val <= PRECOMPILE_COUNT
                        ):
                            # skip precompile calls
                            state_index += 1
                            continue
                        if (
                            meminstart.type == VarType.CONCRETE
                            and meminsz.type == VarType.CONCRETE
                        ):
                            self.calls.append(
                                Call(
                                    self.nodes[key],
                                    state,
                                    state_index,
                                    op,
                                    to,
                                    gas,
                                    value,
                                    state.mstate.memory[
                                        meminstart.val : meminsz.val + meminstart.val
                                    ],
                                )
                            )
                        else:
                            self.calls.append(
                                Call(
                                    self.nodes[key],
                                    state,
                                    state_index,
                                    op,
                                    to,
                                    gas,
                                    value,
                                )
                            )
                    else:
                        gas, to = get_variable(stack[-1]), get_variable(stack[-2])
                        self.calls.append(
                            Call(self.nodes[key], state, state_index, op, to, gas)
                        )
                state_index += 1

    @property
    def execution_info(self) -> List[ExecutionInfo]:
        return self.laser.execution_info
