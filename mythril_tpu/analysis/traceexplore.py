"""Serializable statespace dump for `myth analyze -j/--statespace-json`.

Reference parity: mythril/analysis/traceexplore.py:52 — nodes with
per-state machine/account snapshots, edges with branch-condition
labels.
"""

from __future__ import annotations

import re

from mythril_tpu.laser.ethereum.cfg import NodeFlags
from mythril_tpu.laser.smt import simplify

colors = [
    {
        "border": "#26996f",
        "background": "#2f7e5b",
        "highlight": {"border": "#fff", "background": "#28a16f"},
    },
    {
        "border": "#9e42b3",
        "background": "#842899",
        "highlight": {"border": "#fff", "background": "#933da6"},
    },
    {
        "border": "#b82323",
        "background": "#991d1d",
        "highlight": {"border": "#fff", "background": "#a61f1f"},
    },
    {
        "border": "#4753bf",
        "background": "#3b46a1",
        "highlight": {"border": "#fff", "background": "#424db3"},
    },
]


def get_serializable_statespace(statespace) -> dict:
    """Convert a finished statespace into JSON-ready nodes and edges."""
    nodes = []
    edges = []

    color_map = {}
    i = 0
    for k in statespace.accounts:
        color_map[statespace.accounts[k].contract_name] = colors[i % len(colors)]
        i += 1

    for node_key in statespace.nodes:
        node = statespace.nodes[node_key]

        code = node.get_cfg_dict()["code"]
        code = re.sub("([0-9a-f]{8})[0-9a-f]+", lambda m: m.group(1) + "(...)", code)
        if NodeFlags.FUNC_ENTRY in node.flags:
            code = re.sub("JUMPDEST", node.function_name, code)
        code_split = code.split("\\n")

        truncated_code = (
            code
            if (len(code_split) < 7)
            else "\\n".join(code_split[:6]) + "\\n(click to expand +)"
        )
        try:
            color = color_map[node.get_cfg_dict()["contract_name"]]
        except KeyError:
            color = colors[i % len(colors)]
            i += 1
            color_map[node.get_cfg_dict()["contract_name"]] = color

        def get_state_accounts(node_state):
            state_accounts = []
            for key in node_state.accounts:
                account = node_state.accounts[key].as_dict
                account.pop("code", None)
                account["balance"] = str(account["balance"])

                storage = {}
                for storage_key in account["storage"].printable_storage:
                    storage[str(storage_key)] = str(account["storage"][storage_key])
                state_accounts.append({"address": key, "storage": storage})
            return state_accounts

        states = []
        for x in node.states:
            machine = x.mstate.as_dict
            machine["stack"] = [str(s) for s in machine["stack"]]
            memory = machine.pop("memory")
            machine["memory"] = [
                str(memory[idx]) for idx in range(min(len(memory), 128))
            ]
            states.append(
                {"machine": machine, "accounts": get_state_accounts(x)}
            )

        truncated_code = truncated_code.replace("\\n", "\n")
        code = code.replace("\\n", "\n")

        nodes.append(
            {
                "id": str(node_key),
                "func": str(node.function_name),
                "label": truncated_code,
                "code": code,
                "truncated": truncated_code,
                "states": states,
                "color": color,
                "instructions": code.split("\n"),
            }
        )

    for edge in statespace.edges:
        if edge.condition is None:
            label = ""
        else:
            label = str(simplify(edge.condition)).replace("\n", "")
        label = re.sub(
            r"([^_])([\d]{2}\d+)", lambda m: m.group(1) + hex(int(m.group(2))), label
        )

        edges.append(
            {
                "from": str(edge.as_dict["from"]),
                "to": str(edge.as_dict["to"]),
                "arrows": "to",
                "label": label,
                "smooth": {"type": "cubicBezier"},
            }
        )

    return {"edges": edges, "nodes": nodes}
