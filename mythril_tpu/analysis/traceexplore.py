"""Serializable statespace dump for `myth analyze -j/--statespace-json`.

Covers mythril/analysis/traceexplore.py: nodes carrying per-state
machine/account snapshots, edges labeled with simplified branch
conditions — the payload the trace-explorer front end renders.
"""

from __future__ import annotations

import re
from itertools import count

from mythril_tpu.laser.ethereum.cfg import NodeFlags
from mythril_tpu.laser.smt import simplify

PALETTE = [
    {
        "border": "#26996f",
        "background": "#2f7e5b",
        "highlight": {"border": "#fff", "background": "#28a16f"},
    },
    {
        "border": "#9e42b3",
        "background": "#842899",
        "highlight": {"border": "#fff", "background": "#933da6"},
    },
    {
        "border": "#b82323",
        "background": "#991d1d",
        "highlight": {"border": "#fff", "background": "#a61f1f"},
    },
    {
        "border": "#4753bf",
        "background": "#3b46a1",
        "highlight": {"border": "#fff", "background": "#424db3"},
    },
]

# kept under its historical name for importers
colors = PALETTE


class _ContractPalette:
    """Stable contract-name -> color assignment."""

    def __init__(self, names):
        self._next = count()
        self._colors = {n: self._pick() for n in names}

    def _pick(self):
        return PALETTE[next(self._next) % len(PALETTE)]

    def color_of(self, name):
        if name not in self._colors:
            self._colors[name] = self._pick()
        return self._colors[name]


def _abbreviate_code(node) -> str:
    """The node's disassembly with long hex blobs elided and the
    function name substituted at entry points."""
    code = node.get_cfg_dict()["code"]
    code = re.sub(
        "([0-9a-f]{8})[0-9a-f]+", lambda m: m.group(1) + "(...)", code
    )
    if NodeFlags.FUNC_ENTRY in node.flags:
        code = re.sub("JUMPDEST", node.function_name, code)
    return code


def _snapshot_accounts(state) -> list:
    out = []
    for address, account in state.accounts.items():
        view = account.as_dict
        view.pop("code", None)
        view["balance"] = str(view["balance"])
        storage = {
            str(k): str(view["storage"][k])
            for k in view["storage"].printable_storage
        }
        out.append({"address": address, "storage": storage})
    return out


def _snapshot_machine(state) -> dict:
    machine = state.mstate.as_dict
    machine["stack"] = [str(word) for word in machine["stack"]]
    memory = machine.pop("memory")
    machine["memory"] = [str(memory[i]) for i in range(min(len(memory), 128))]
    return machine


def _edge_label(edge) -> str:
    if edge.condition is None:
        return ""
    label = str(simplify(edge.condition)).replace("\n", "")
    # big decimal literals read better as hex
    return re.sub(
        r"([^_])([\d]{2}\d+)",
        lambda m: m.group(1) + hex(int(m.group(2))),
        label,
    )


def get_serializable_statespace(statespace) -> dict:
    """Convert a finished statespace into JSON-ready nodes and edges."""
    palette = _ContractPalette(
        statespace.accounts[k].contract_name for k in statespace.accounts
    )

    nodes = []
    for node_key, node in statespace.nodes.items():
        code = _abbreviate_code(node)
        lines = code.split("\\n")
        preview = (
            code
            if len(lines) < 7
            else "\\n".join(lines[:6]) + "\\n(click to expand +)"
        )
        preview = preview.replace("\\n", "\n")
        code = code.replace("\\n", "\n")

        nodes.append(
            {
                "id": str(node_key),
                "func": str(node.function_name),
                "label": preview,
                "code": code,
                "truncated": preview,
                "states": [
                    {
                        "machine": _snapshot_machine(s),
                        "accounts": _snapshot_accounts(s),
                    }
                    for s in node.states
                ],
                "color": palette.color_of(node.get_cfg_dict()["contract_name"]),
                "instructions": code.split("\n"),
            }
        )

    edges = [
        {
            "from": str(edge.as_dict["from"]),
            "to": str(edge.as_dict["to"]),
            "arrows": "to",
            "label": _edge_label(edge),
            "smooth": {"type": "cubicBezier"},
        }
        for edge in statespace.edges
    ]

    return {"edges": edges, "nodes": nodes}
