"""Run detection modules over a finished analysis.

Reference parity: mythril/analysis/security.py:15-46 —
`retrieve_callback_issues` collects what the hook-driven modules found
during execution; `fire_lasers` additionally runs POST modules over
the statespace.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from mythril_tpu.analysis.module import ModuleLoader, reset_callback_modules
from mythril_tpu.analysis.module.base import EntryPoint
from mythril_tpu.analysis.report import Issue

log = logging.getLogger(__name__)


def retrieve_callback_issues(white_list: Optional[List[str]] = None) -> List[Issue]:
    """Issues discovered by callback detection modules during the run."""
    issues: List[Issue] = []
    for module in ModuleLoader().get_detection_modules(
        entry_point=EntryPoint.CALLBACK, white_list=white_list
    ):
        log.debug("Retrieving results for %s", module.name)
        issues += module.issues
    reset_callback_modules(module_names=white_list)
    return issues


def fire_lasers(statespace, white_list: Optional[List[str]] = None) -> List[Issue]:
    """Run POST modules over the statespace and collect all issues,
    merging in the concrete witnesses the device prepass banked
    (analysis/prepass.py) for locations the host walk did not reach.

    The static pre-screen (analysis/static, computed by
    SymExecWrapper) filters modules whose opcode signature cannot fire
    on the analyzed code — they neither mounted hooks nor run their
    POST pass. White-list validation still happens first, so an
    invalid -m name errors regardless of the screen."""
    log.info("Starting analysis")
    screen = getattr(statespace, "static_screen", None)
    issues: List[Issue] = []
    for module in ModuleLoader().get_detection_modules(
        entry_point=EntryPoint.POST, white_list=white_list
    ):
        if screen is not None and type(module).__name__ not in screen:
            log.debug("Static pre-screen skipped %s", module.name)
            continue
        log.info("Executing %s", module.name)
        issues += module.execute(statespace)
    issues += retrieve_callback_issues(white_list)

    device_issues = getattr(statespace, "device_issues", None) or []
    if white_list:
        # honor the user's module selection per finding class: a
        # device witness stands in for exactly one module's finding
        # SWC-107 is claimed by two modules with distinct titles, so
        # the filter keys on (swc, title); None matches any title
        module_claims = {
            "Exceptions": (("110", None),),
            "AccidentallyKillable": (("106", None),),
            "IntegerArithmetics": (("101", None),),
            "UncheckedRetval": (("104", None),),
            "EtherThief": (("105", None),),
            "ExternalCalls": (
                ("107", "External Call To User-Supplied Address"),
            ),
            "StateChangeAfterCall": (
                ("107", "State access after external call"),
            ),
            "ArbitraryDelegateCall": (("112", None),),
            "TxOrigin": (("115", None),),
            "PredictableVariables": (("116", None), ("120", None)),
        }
        allowed = set()
        for module_name, claims in module_claims.items():
            if module_name in white_list:
                allowed.update(claims)
        device_issues = [
            i
            for i in device_issues
            if (i.swc_id, None) in allowed or (i.swc_id, i.title) in allowed
        ]
    if device_issues:
        seen = {
            (issue.contract, issue.address, issue.swc_id) for issue in issues
        }
        fresh = [
            issue
            for issue in device_issues
            if (issue.contract, issue.address, issue.swc_id) not in seen
        ]
        if fresh:
            log.info(
                "Device prepass contributed %d issue(s) the host walk "
                "did not find",
                len(fresh),
            )
        issues += fresh
    return issues
