"""Analysis / detection layer (reference: mythril/analysis/)."""
