"""Deterministic benchmark-corpus synthesis.

BASELINE.json config 3 calls for a 1k-contract SWC-style corpus; the
image ships only the reference's 13 precompiled fixtures
(tests/testdata/inputs/*.sol.o — the inputs the reference's own CLI
tests analyze). This module synthesizes an arbitrarily large corpus
from them by *constant mutation*: each replica keeps the original's
control-flow graph byte-for-byte but carries distinct function
selectors, addresses, and data constants, so no two replicas share
hash-consed terms, solver queries, or calldata witnesses — every
contract costs the analyzer real work, exactly like a family of
forked/redeployed contracts on mainnet (the regime the reference's
per-contract loop, mythril/mythril/mythril_analyzer.py:145-185, was
built for).

What is mutated (and why it is structure-preserving):

- the 4-byte immediate of a ``PUSH4`` directly followed by ``EQ`` —
  the Solidity dispatcher's selector-compare idiom (the same pattern
  the disassembler's function-recovery matches,
  mythril/disassembler/disassembly.py:63). New selectors re-route
  which calldata reaches which function but leave every jump target
  untouched.
- ``PUSH20`` immediates — hardcoded addresses.
- the low half of a ``PUSH32`` immediate when the value is not a
  mask/sentinel (not mostly 0x00/0xff bytes) — data constants.

Jump destinations are never touched: PUSH1..PUSH3 immediates (memory
offsets, jumpdests, small constants) and mask-like words are left
alone, so every replica disassembles to the same instruction skeleton
and exercises the same paths under symbolic calldata.

Determinism: the byte stream is a pure function of (family name,
replica index, corpus seed); two processes synthesize identical
corpora.
"""

from __future__ import annotations

import random
from pathlib import Path
from typing import List, Optional, Tuple

PUSH1, PUSH32 = 0x60, 0x7F


def _instruction_starts(code: bytes) -> List[int]:
    """Offsets of instruction starts (linear sweep, PUSH data skipped)
    — mutation must never rewrite a byte that another sweep would read
    as an opcode."""
    starts = []
    pc = 0
    while pc < len(code):
        starts.append(pc)
        op = code[pc]
        pc += 1 + (op - PUSH1 + 1 if PUSH1 <= op <= PUSH32 else 0)
    return starts


def _masklike(word: bytes) -> bool:
    """True for sentinel/mask words (mostly 0x00/0xff or few distinct
    bytes) whose value is semantic — address masks, type(uint).max,
    -1 — rather than data."""
    extreme = sum(1 for b in word if b in (0x00, 0xFF))
    return extreme >= len(word) - 2 or len(set(word)) <= 2


def mutate_constants(code: bytes, rng: random.Random) -> bytes:
    """One structure-preserving replica of `code` (see module doc)."""
    out = bytearray(code)
    starts = _instruction_starts(code)
    for i, pc in enumerate(starts):
        op = code[pc]
        if not PUSH1 <= op <= PUSH32:
            continue
        width = op - PUSH1 + 1
        arg = bytes(code[pc + 1 : pc + 1 + width])
        if len(arg) < width:
            continue  # truncated trailing push (swarm hash tail)
        nxt = code[starts[i + 1]] if i + 1 < len(starts) else None
        if width == 4 and nxt == 0x14:  # PUSH4 <sel>; EQ — dispatcher
            out[pc + 1 : pc + 5] = rng.randbytes(4)
        elif width == 20:
            out[pc + 1 : pc + 21] = rng.randbytes(20)
        elif width == 32 and not _masklike(arg):
            out[pc + 17 : pc + 33] = rng.randbytes(16)
    return bytes(out)


def fixture_dir() -> Path:
    # one resolution rule for all fixture consumers: override ->
    # vendored in-repo copy -> reference checkout (goldens.py)
    from mythril_tpu.analysis.goldens import GOLDEN_FIXTURES

    return GOLDEN_FIXTURES


def load_fixtures(
    inputs: Optional[Path] = None,
) -> List[Tuple[str, str]]:
    """[(family name, runtime hex)] for every precompiled fixture."""
    inputs = inputs or fixture_dir()
    out = []
    for f in sorted(inputs.glob("*.sol.o")):
        code = f.read_text().strip()
        if code.startswith("0x"):
            code = code[2:]
        if len(code) >= 8:
            out.append((f.stem, code))
    return out


def synth_corpus(
    n_contracts: int,
    seed: int = 2024,
    inputs: Optional[Path] = None,
) -> List[Tuple[str, str, str]]:
    """`n_contracts` (runtime_hex, creation_hex="", name) rows, the
    analyze_corpus input shape. Families round-robin; replica 0 of
    each family is the unmutated original so the corpus contains the
    real fixtures, and replica k > 0 is the k-th constant mutation."""
    families = load_fixtures(inputs)
    if not families:
        return []
    corpus: List[Tuple[str, str, str]] = []
    replica = 0
    while len(corpus) < n_contracts:
        for name, code_hex in families:
            if len(corpus) >= n_contracts:
                break
            if replica == 0:
                mutant_hex = code_hex
            else:
                rng = random.Random(f"{seed}:{name}:{replica}")
                mutant_hex = mutate_constants(
                    bytes.fromhex(code_hex), rng
                ).hex()
            corpus.append((mutant_hex, "", f"{name}#{replica}"))
        replica += 1
    return corpus


def _check_skeleton(original: bytes, mutant: bytes) -> bool:
    """Same instruction skeleton: identical opcode bytes at identical
    offsets (only PUSH immediates may differ)."""
    if len(original) != len(mutant):
        return False
    starts = _instruction_starts(original)
    return starts == _instruction_starts(mutant) and all(
        original[pc] == mutant[pc] for pc in starts
    )
