"""Deterministic benchmark-corpus synthesis.

BASELINE.json config 3 calls for a 1k-contract SWC-style corpus; the
image ships only the reference's 13 precompiled fixtures
(tests/testdata/inputs/*.sol.o — the inputs the reference's own CLI
tests analyze). This module synthesizes an arbitrarily large corpus
from them by *constant mutation*: each replica keeps the original's
control-flow graph byte-for-byte but carries distinct function
selectors, addresses, and data constants, so no two replicas share
hash-consed terms, solver queries, or calldata witnesses — every
contract costs the analyzer real work, exactly like a family of
forked/redeployed contracts on mainnet (the regime the reference's
per-contract loop, mythril/mythril/mythril_analyzer.py:145-185, was
built for).

What is mutated (and why it is structure-preserving):

- the 4-byte immediate of a ``PUSH4`` directly followed by ``EQ`` —
  the Solidity dispatcher's selector-compare idiom (the same pattern
  the disassembler's function-recovery matches,
  mythril/disassembler/disassembly.py:63). New selectors re-route
  which calldata reaches which function but leave every jump target
  untouched.
- ``PUSH20`` immediates — hardcoded addresses.
- the low half of a ``PUSH32`` immediate when the value is not a
  mask/sentinel (not mostly 0x00/0xff bytes) — data constants.

Jump destinations are never touched: PUSH1..PUSH3 immediates (memory
offsets, jumpdests, small constants) and mask-like words are left
alone, so every replica disassembles to the same instruction skeleton
and exercises the same paths under symbolic calldata.

Determinism: the byte stream is a pure function of (family name,
replica index, corpus seed); two processes synthesize identical
corpora.
"""

from __future__ import annotations

import random
from pathlib import Path
from typing import List, Optional, Tuple

PUSH1, PUSH32 = 0x60, 0x7F


def _instruction_starts(code: bytes) -> List[int]:
    """Offsets of instruction starts (linear sweep, PUSH data skipped)
    — mutation must never rewrite a byte that another sweep would read
    as an opcode."""
    starts = []
    pc = 0
    while pc < len(code):
        starts.append(pc)
        op = code[pc]
        pc += 1 + (op - PUSH1 + 1 if PUSH1 <= op <= PUSH32 else 0)
    return starts


def _masklike(word: bytes) -> bool:
    """True for sentinel/mask words (mostly 0x00/0xff or few distinct
    bytes) whose value is semantic — address masks, type(uint).max,
    -1 — rather than data."""
    extreme = sum(1 for b in word if b in (0x00, 0xFF))
    return extreme >= len(word) - 2 or len(set(word)) <= 2


def mutate_constants(code: bytes, rng: random.Random) -> bytes:
    """One structure-preserving replica of `code` (see module doc)."""
    out = bytearray(code)
    starts = _instruction_starts(code)
    for i, pc in enumerate(starts):
        op = code[pc]
        if not PUSH1 <= op <= PUSH32:
            continue
        width = op - PUSH1 + 1
        arg = bytes(code[pc + 1 : pc + 1 + width])
        if len(arg) < width:
            continue  # truncated trailing push (swarm hash tail)
        nxt = code[starts[i + 1]] if i + 1 < len(starts) else None
        if width == 4 and nxt == 0x14:  # PUSH4 <sel>; EQ — dispatcher
            out[pc + 1 : pc + 5] = rng.randbytes(4)
        elif width == 20:
            out[pc + 1 : pc + 21] = rng.randbytes(20)
        elif width == 32 and not _masklike(arg):
            out[pc + 17 : pc + 33] = rng.randbytes(16)
    return bytes(out)


def fixture_dir() -> Path:
    # one resolution rule for all fixture consumers: override ->
    # vendored in-repo copy -> reference checkout (goldens.py)
    from mythril_tpu.analysis.goldens import GOLDEN_FIXTURES

    return GOLDEN_FIXTURES


def load_fixtures(
    inputs: Optional[Path] = None,
) -> List[Tuple[str, str]]:
    """[(family name, runtime hex)] for every precompiled fixture."""
    inputs = inputs or fixture_dir()
    out = []
    for f in sorted(inputs.glob("*.sol.o")):
        code = f.read_text().strip()
        if code.startswith("0x"):
            code = code[2:]
        if len(code) >= 8:
            out.append((f.stem, code))
    return out


def synth_corpus(
    n_contracts: int,
    seed: int = 2024,
    inputs: Optional[Path] = None,
) -> List[Tuple[str, str, str]]:
    """`n_contracts` (runtime_hex, creation_hex="", name) rows, the
    analyze_corpus input shape. Families round-robin; replica 0 of
    each family is the unmutated original so the corpus contains the
    real fixtures, and replica k > 0 is the k-th constant mutation."""
    families = load_fixtures(inputs)
    if not families:
        return []
    corpus: List[Tuple[str, str, str]] = []
    replica = 0
    while len(corpus) < n_contracts:
        for name, code_hex in families:
            if len(corpus) >= n_contracts:
                break
            if replica == 0:
                mutant_hex = code_hex
            else:
                rng = random.Random(f"{seed}:{name}:{replica}")
                mutant_hex = mutate_constants(
                    bytes.fromhex(code_hex), rng
                ).hex()
            corpus.append((mutant_hex, "", f"{name}#{replica}"))
        replica += 1
    return corpus


def loop_contract(iterations_cap: int = 0x7F) -> str:
    """A hand-assembled deep-loop runtime (BASELINE config-4 shape):
    `n = calldata[0..31] & cap; while (n) { acc += n; n -= 1 };
    storage[0] = acc; if (calldata[32] == 0xaa) assert(false)` — the
    loop count is attacker-chosen, so bounded-loop strategies and the
    device wave budget both get exercised, and the tail assert keeps a
    detectable SWC-110 behind real loop work."""
    loop = 0x0A  # JUMPDEST lands right after the 10-byte prologue
    # prologue: n = CALLDATALOAD(0) & cap; acc = 0 (stack [acc, n])
    code = bytes(
        [0x60, 0x00, 0x35, 0x60, iterations_cap & 0xFF, 0x16, 0x60, 0x00]
    )
    code += bytes([0x90, 0x90])  # two SWAP1s (net no-op padding)
    body = bytes(
        [
            0x5B,  # loop: JUMPDEST           [acc, n]
            0x81, 0x15,  # DUP2; ISZERO       [n==0, acc, n]
            0x60, 0x00,  # PUSH1 exit (patched below)
            0x57,  # JUMPI                    [acc, n]
            0x81, 0x01,  # DUP2; ADD          [acc+n, n]
            0x90,  # SWAP1                    [n, acc']
            0x60, 0x01, 0x90, 0x03,  # PUSH1 1; SWAP1; SUB -> [n-1, acc']
            0x90,  # SWAP1                    [acc', n-1]
            0x60, loop, 0x56,  # PUSH1 loop; JUMP
        ]
    )
    exit_at = loop + len(body)
    body = body.replace(bytes([0x60, 0x00, 0x57]), bytes([0x60, exit_at, 0x57]))
    # exit: storage[0] = acc; if (calldata[32..63] == 0xaa) INVALID
    tail = bytes([0x5B, 0x60, 0x00, 0x55])  # JUMPDEST; SSTORE
    guard_at = exit_at + len(tail)
    fail_at = guard_at + 10
    tail += bytes(
        [
            0x60, 0x20, 0x35,  # PUSH1 32; CALLDATALOAD
            0x60, 0xAA, 0x14,  # == 0xaa ?
            0x60, fail_at, 0x57,  # JUMPI fail
            0x00,  # STOP
            0x5B, 0xFE,  # fail: JUMPDEST; INVALID (SWC-110)
        ]
    )
    return (code + body + tail).hex()


def degrader_contract(copy_at: int = 0x2000) -> str:
    """A runtime whose first action writes calldata FAR past the lean
    device memory cap (CALLDATACOPY to `copy_at`): device lanes demote
    to ERR_MEM and the host takeover carries the contract — the shape
    that makes the degradation counters a measured number instead of a
    structural claim. A guarded INVALID behind the copy keeps a real
    SWC-110 for the host to find."""
    code = bytes(
        [
            0x60, 0x20,  # PUSH1 32 (length)
            0x60, 0x00,  # PUSH1 0 (calldata offset)
            0x61, (copy_at >> 8) & 0xFF, copy_at & 0xFF,  # PUSH2 dest
            0x39,  # CALLDATACOPY
        ]
    )
    guard_at = len(code)
    fail_at = guard_at + 10
    code += bytes(
        [
            0x60, 0x00, 0x35,  # CALLDATALOAD(0)
            0x60, 0xAA, 0x14,  # == 0xaa ? (whole-word compare)
            0x60, fail_at, 0x57,  # JUMPI fail
            0x00,  # STOP
            0x5B, 0xFE,  # fail: JUMPDEST; INVALID
        ]
    )
    return code.hex()


def wide_contract(n_guards: int = 6, seed: int = 0) -> str:
    """A hand-assembled wide-branching runtime — the shape where the
    device engine's breadth is a STRUCTURAL advantage, not a constant
    factor. `n_guards` independent calldata guards (each its own
    32-byte word vs a distinct magic constant) plus an
    overflow-to-branch segment, an ORIGIN guard, a TIMESTAMP guard,
    and a guarded SELFDESTRUCT:

        if (cd[4+32j] == magic_j)  { mem[j] = 1 }        // j guards
        if (cd[o_w] + C == 0)      { mem[7] = 1 }        // ADD wraps (SWC-101)
        if (tx.origin == A)        { mem[8] = 1 }        // SWC-115
        if (block.timestamp == T)  { mem[9] = 1 }        // SWC-116
        if (cd[o_k] == magic_k)    { selfdestruct(caller) }  // SWC-106

    A sequential symbolic walk forks at every guard: ~2^(n_guards+4)
    path-leaves, two feasibility solves per fork (the reference's
    worklist shape, mythril/laser/ethereum/svm.py:235-271) — the
    per-contract wall grows exponentially. Branch-coverage closure on
    the device needs ONE flip per guard direction: a couple of waves
    regardless of 2^K. Storage is never written, so tx-2 starts from
    unchanged states on both engines (no carry variance)."""
    rng = random.Random(0xBEEF + seed)
    code = bytearray()

    def _guard_cd(offset: int, magic: int, body: bytes) -> None:
        # PUSH2 off CALLDATALOAD PUSH4 magic EQ ISZERO PUSH2 skip JUMPI
        code.extend([0x61, (offset >> 8) & 0xFF, offset & 0xFF, 0x35])
        code.extend([0x63]) ; code.extend(magic.to_bytes(4, "big"))
        code.extend([0x14, 0x15])
        skip = len(code) + 3 + 1 + len(body)
        code.extend([0x61, (skip >> 8) & 0xFF, skip & 0xFF, 0x57])
        code.extend(body)
        code.extend([0x5B])  # skip: JUMPDEST

    def _mark(j: int) -> bytes:
        return bytes([0x60, 0x01, 0x60, j & 0xFF, 0x53])  # mem[j] = 1

    for j in range(n_guards):
        _guard_cd(4 + 32 * j, 0xFEED0000 + rng.getrandbits(16), _mark(j))

    # overflow-to-branch: s = cd[o_w] + C; if (s == 0) { mem[7] = 1 }
    # the s == 0 witness is exactly the wrapping input, and the JUMPI
    # is integer.py's promotion site on both engines
    o_w = 4 + 32 * n_guards
    big = (2**256 - (0x10000 + rng.getrandbits(12))) | 1
    code.extend([0x61, (o_w >> 8) & 0xFF, o_w & 0xFF, 0x35])
    code.extend([0x7F]) ; code.extend(big.to_bytes(32, "big"))
    code.extend([0x01, 0x60, 0x00, 0x14, 0x15])
    skip = len(code) + 3 + 1 + 5
    code.extend([0x61, (skip >> 8) & 0xFF, skip & 0xFF, 0x57])
    code.extend(_mark(7))
    code.extend([0x5B])

    # ORIGIN guard: equality with an address the pinned replay origin
    # does not match — the taken direction is host-only (symbolic
    # origin), the branch itself banks SWC-115 from the DAG either way
    code.extend([0x32, 0x73]) ; code.extend((0xAAAA000000000000000000000000000000000000 + seed).to_bytes(20, "big"))
    code.extend([0x14, 0x15])
    skip = len(code) + 3 + 1 + 5
    code.extend([0x61, (skip >> 8) & 0xFF, skip & 0xFF, 0x57])
    code.extend(_mark(8))
    code.extend([0x5B])

    # TIMESTAMP guard (SWC-116): same shape
    code.extend([0x42, 0x63]) ; code.extend((0x5C000000 + seed).to_bytes(4, "big"))
    code.extend([0x14, 0x15])
    skip = len(code) + 3 + 1 + 5
    code.extend([0x61, (skip >> 8) & 0xFF, skip & 0xFF, 0x57])
    code.extend(_mark(9))
    code.extend([0x5B])

    # guarded SELFDESTRUCT(caller) — last: it ends the transaction
    o_k = o_w + 32
    _guard_cd(o_k, 0xDEAD0000 + rng.getrandbits(16), bytes([0x33, 0xFF]))
    code.extend([0x00])  # STOP
    return bytes(code).hex()


def bec_contract(seed: int = 0) -> str:
    """The BECToken shape (SWC-101 CVE-2018-10299): an unchecked
    `amount = cnt * value` whose product then steers control flow
    through a DIVISION — `if (m / y == x) { sstore }`. The flip of
    that branch (`m / y != x` with the mul in scope) is exactly the
    multiplication+division circuit CDCL grinds on (measured: 33.6s
    for the native CDCL; the on-chip portfolio's concrete evaluation
    finds a witness in seconds — the workload class where the solver
    race pays). An assert guard rides behind it for a detectable
    SWC-110."""
    # offsets are fixed by construction (PUSH2 jump forms throughout):
    #  0: x = cd(4); 3: y = cd(36); 6: if (y == 0) goto end
    # 12: m = x*y; 15: q = m/y; 18..24: if (q != x) goto skip
    # 25: sstore(0,1); 30 skip: guard cd(68) == magic -> fail
    # 41 end: STOP; 43 fail: INVALID
    skip, end, fail = 30, 41, 43
    code = bytearray(
        [
            0x60, 0x04, 0x35,        # x = CALLDATALOAD(4)
            0x60, 0x24, 0x35,        # y = CALLDATALOAD(36)  [x, y]
            0x80, 0x15,              # DUP1 ISZERO           [x, y, y==0]
            0x61, (end >> 8) & 0xFF, end & 0xFF, 0x57,  # JUMPI end
            0x81, 0x81, 0x02,        # DUP2 DUP2 MUL -> m    [x, y, m]
            0x81, 0x90, 0x04,        # DUP2 SWAP1 DIV -> m/y [x, y, q]
            0x82, 0x14,              # DUP3 EQ -> q == x     [x, y, e]
            0x15,                    # ISZERO                [x, y, !e]
            0x61, (skip >> 8) & 0xFF, skip & 0xFF, 0x57,  # JUMPI skip
            0x60, 0x01, 0x60, 0x00, 0x55,  # sstore(0, 1)
            0x5B,                    # skip: JUMPDEST
            0x60, 0x44, 0x35,              # CALLDATALOAD(68)
            0x60, 0xAA + (seed % 16), 0x14,  # == 0xaa+k ?
            0x61, (fail >> 8) & 0xFF, fail & 0xFF, 0x57,
            0x5B, 0x00,                    # end: JUMPDEST; STOP
            0x5B, 0xFE,                    # fail: JUMPDEST; INVALID
        ]
    )
    return bytes(code).hex()


def deadweight_contract(seed: int = 0) -> str:
    """A runtime full of statically-resolvable waste — the shape the
    static layer (analysis/static) exists to keep off the arena:

    - a constant-true guard (`PUSH1 1; PUSH1 t; JUMPI`) whose
      fall-through is a dead island (const-foldable dead direction +
      unreachable code);
    - a dispatcher with a LIVE function (SSTORE + a guarded INVALID,
      so the contract keeps a detectable SWC-110) and a DEAD function
      (`JUMPDEST PUSH1 0 DUP1 REVERT` — the classic inert revert
      body) whose seeds/flips static pruning drops.

    With pruning on and off, the ISSUE set is identical by
    construction — only the wasted lanes differ."""
    dead_fn, live_fn = 35, 40
    fail_at = 56
    live_sel = (0xFEEDC0DE + seed) & 0xFFFFFFFF
    dead_sel = (0xDEADD00D + seed * 7) & 0xFFFFFFFF
    code = bytearray(
        [
            0x60, 0x01, 0x60, 0x07, 0x57,  # PUSH1 1; PUSH1 7; JUMPI
            0x00, 0xFE,                    # dead island
            0x5B,                          # 7: JUMPDEST
            0x60, 0x00, 0x35,              # CALLDATALOAD(0)
            0x60, 0xE0, 0x1C,              # >> 224 -> selector
            0x80, 0x63,                    # DUP1; PUSH4
        ]
    )
    code += live_sel.to_bytes(4, "big")
    code += bytes([0x14, 0x60, live_fn, 0x57])  # EQ; PUSH1 live; JUMPI
    code += bytes([0x80, 0x63]) + dead_sel.to_bytes(4, "big")
    code += bytes([0x14, 0x60, dead_fn, 0x57])  # EQ; PUSH1 dead; JUMPI
    code += bytes([0x00])  # STOP (no match)
    assert len(code) == dead_fn
    code += bytes([0x5B, 0x60, 0x00, 0x80, 0xFD])  # dead: revert(0,0)
    assert len(code) == live_fn
    code += bytes([0x5B, 0x60, 0x01, 0x60, 0x00, 0x55])  # sstore(0,1)
    code += bytes([0x60, 0x04, 0x35])  # CALLDATALOAD(4)
    code += bytes([0x60, 0xAA + (seed % 16), 0x14])  # == magic?
    code += bytes([0x60, fail_at, 0x57, 0x00])  # JUMPI fail; STOP
    assert len(code) == fail_at
    code += bytes([0x5B, 0xFE])  # fail: JUMPDEST; INVALID (SWC-110)
    return bytes(code).hex()


def clean_contract(seed: int = 0) -> str:
    """A provably-clean runtime — the static-answer triage tier's
    positive shape. Two-selector dispatcher whose bodies do only what
    the semantic screen can discharge: constant-slot SSTORE (the
    arbitrary-write sentinel is unsatisfiable), constant non-wrapping
    ADD (no overflow annotation possible), constant MSTORE with no
    LOG1/marker (the UserAssertions evidence test), constant jump
    targets throughout. Every detection module screens off, so the
    triage tier answers it with an empty issue set — which IS its
    true issue set, keeping the prune differential trivially equal."""
    sel1 = (0xC0FFEE00 + seed) & 0xFFFFFFFF
    sel2 = (0x0DDBA110 + seed * 3) & 0xFFFFFFFF
    store_fn, return_fn = 0x1A, 0x24
    code = bytearray(
        [0x60, 0x00, 0x35, 0x60, 0xE0, 0x1C, 0x80, 0x63]
    )  # selector = CALLDATALOAD(0) >> 224; DUP1; PUSH4
    code += sel1.to_bytes(4, "big")
    code += bytes([0x14, 0x60, store_fn, 0x57])  # EQ; PUSH1 a; JUMPI
    code += bytes([0x63]) + sel2.to_bytes(4, "big")
    code += bytes([0x14, 0x60, return_fn, 0x57])  # EQ; PUSH1 b; JUMPI
    code += bytes([0x00])  # STOP (no match)
    assert len(code) == store_fn
    # a: sstore(0, 1 + (2 + k))  — constant, non-wrapping
    code += bytes(
        [0x5B, 0x60, 0x01, 0x60, 0x02 + (seed % 16), 0x01,
         0x60, 0x00, 0x55, 0x00]
    )
    assert len(code) == return_fn
    # b: return mem[0:32] = 42 — a constant MSTORE, no marker word
    code += bytes(
        [0x5B, 0x60, 0x2A, 0x60, 0x00, 0x52, 0x60, 0x20, 0x60, 0x00,
         0xF3]
    )
    return bytes(code).hex()


def fork_contract(seed: int = 0, variant: int = 0) -> str:
    """A two-function dispatcher whose fork axis is ONE selector: the
    verdict store's incremental-reanalysis shape. `seed` fixes the
    selectors (all variants of one seed share them); `variant` mutates
    fn A's body constants only (its SSTORE value and INVALID-guard
    magic), so between two variants exactly fn A's subgraph
    fingerprint changes while fn B — which carries its own guarded
    INVALID (SWC-110) and touches no storage — stays byte-identical.
    fn B reads no storage, so the incremental diff's cross-selector
    state-flow bail stays quiet and its banked issues are mergeable.

        fn A: sstore(0, C_v); if (cd[4..35] == magic_v) INVALID
        fn B: if (cd[4..35] == 0xbb) INVALID
    """
    sel1 = (0xF0CACC1A + seed) & 0xFFFFFFFF
    sel2 = (0xBA5EBA11 + seed * 5) & 0xFFFFFFFF
    fn_a, fn_b = 26, 44
    fail_a, fail_b = 42, 55
    code = bytearray(
        [0x60, 0x00, 0x35, 0x60, 0xE0, 0x1C, 0x80, 0x63]
    )  # selector = CALLDATALOAD(0) >> 224; DUP1; PUSH4
    code += sel1.to_bytes(4, "big")
    code += bytes([0x14, 0x60, fn_a, 0x57])  # EQ; PUSH1 a; JUMPI
    code += bytes([0x63]) + sel2.to_bytes(4, "big")
    code += bytes([0x14, 0x60, fn_b, 0x57])  # EQ; PUSH1 b; JUMPI
    code += bytes([0x00])  # STOP (no match)
    assert len(code) == fn_a
    code += bytes([0x5B, 0x60, 0x10 + (variant % 0xE0)])  # PUSH1 C_v
    code += bytes([0x60, 0x00, 0x55])  # sstore(0, C_v)
    code += bytes([0x60, 0x04, 0x35])  # CALLDATALOAD(4)
    code += bytes([0x60, 0xA0 + ((seed + variant) % 0x5F), 0x14])
    code += bytes([0x60, fail_a, 0x57, 0x00])  # JUMPI fail_a; STOP
    assert len(code) == fail_a
    code += bytes([0x5B, 0xFE])  # fail_a: JUMPDEST; INVALID
    assert len(code) == fn_b
    code += bytes([0x5B, 0x60, 0x04, 0x35])  # b: CALLDATALOAD(4)
    code += bytes([0x60, 0xBB, 0x14])  # == 0xbb ?
    code += bytes([0x60, fail_b, 0x57, 0x00])  # JUMPI fail_b; STOP
    assert len(code) == fail_b
    code += bytes([0x5B, 0xFE])  # fail_b: JUMPDEST; INVALID
    return bytes(code).hex()


def proxy_pair(
    seed: int = 0, variant: int = 0, collide: bool = False
) -> List[Tuple[str, str, str]]:
    """An EIP-1967 proxy + implementation row pair, the linker's
    known-positive population. The proxy's FORWARD selector does
    `DELEGATECALL(gas, SLOAD(eip1967-impl-slot), calldata)` (the
    proxy-slot provenance class); its ADMIN selector — the real
    `upgradeTo` selector — stores a PUSH20 implementation address
    into the slot (the runtime slot binding the linker resolves the
    edge through) plus a slot-0 counter write, then ends in a guarded
    INVALID so the store has an admin-attributed issue to bank.

    The implementation's address rides its row NAME
    (``impl#<seed>v<variant>@0x<addr>`` — the LinkSet address-book
    convention); the address depends only on `seed`, so two variants
    model an UPGRADE: same proxy bytes, same address, new callee code
    (`variant` mutates the impl's stored constant and guard magic —
    exactly one selector's linked fingerprint moves). `collide=True`
    makes the implementation write slot 0 — the slot the proxy's
    admin counter uses — lighting up `proxy-storage-collision`."""
    from mythril_tpu.analysis.static.callgraph import EIP1967_IMPL_SLOT

    sel_fwd = (0xCA11AB1E + seed) & 0xFFFFFFFF
    sel_adm = 0x3659CFE6  # upgradeTo(address)
    impl_addr = (0x1A << 152) | ((0xBEEF0000 + seed) & 0xFFFFFFFF)
    fn_fwd, fn_adm, fail_adm = 26, 72, 143

    proxy = bytearray([0x60, 0x00, 0x35, 0x60, 0xE0, 0x1C, 0x80, 0x63])
    proxy += sel_fwd.to_bytes(4, "big")
    proxy += bytes([0x14, 0x60, fn_fwd, 0x57])
    proxy += bytes([0x63]) + sel_adm.to_bytes(4, "big")
    proxy += bytes([0x14, 0x60, fn_adm, 0x57])
    proxy += bytes([0x00])  # no match: STOP
    assert len(proxy) == fn_fwd
    # forward: delegatecall(GAS, sload(impl_slot), 0, cds, 0, 0)
    proxy += bytes([0x5B, 0x60, 0x00, 0x60, 0x00, 0x36, 0x60, 0x00])
    proxy += bytes([0x7F]) + EIP1967_IMPL_SLOT.to_bytes(32, "big")
    proxy += bytes([0x54, 0x5A, 0xF4, 0x50, 0x00])
    assert len(proxy) == fn_adm
    # admin: sstore(impl_slot, PUSH20 impl_addr); sstore(0, 1);
    # guarded INVALID (the bankable SWC-110)
    proxy += bytes([0x5B, 0x73]) + impl_addr.to_bytes(20, "big")
    proxy += bytes([0x7F]) + EIP1967_IMPL_SLOT.to_bytes(32, "big")
    proxy += bytes([0x55])
    proxy += bytes([0x60, 0x01, 0x60, 0x00, 0x55])
    proxy += bytes([0x60, 0x04, 0x35, 0x60, 0xAA, 0x14])
    proxy += bytes([0x60, fail_adm, 0x57, 0x00])
    assert len(proxy) == fail_adm
    proxy += bytes([0x5B, 0xFE])

    impl = _linked_leaf(
        selector=sel_fwd,
        value=0x10 + (variant % 0xE0),
        slot=0x00 if collide else 0x01,
        magic=0xA0 + ((seed + 7 * variant) % 0x5F),
    )
    return [
        (bytes(proxy).hex(), "", f"proxy#{seed}"),
        (impl, "", f"impl#{seed}v{variant}@0x{impl_addr:040x}"),
    ]


def minimal_proxy(seed: int = 0) -> List[Tuple[str, str, str]]:
    """An EIP-1167 minimal proxy (the 45-byte literal runtime) plus
    its constant callee — the `minimal-proxy` provenance class, where
    the target address sits IN the bytecode, no taint pass needed."""
    from mythril_tpu.analysis.static.callgraph import (
        MINIMAL_PROXY_PREFIX,
        MINIMAL_PROXY_SUFFIX,
    )

    target_addr = (0x2B << 152) | ((0xC10E0000 + seed) & 0xFFFFFFFF)
    code = (
        MINIMAL_PROXY_PREFIX
        + target_addr.to_bytes(20, "big")
        + MINIMAL_PROXY_SUFFIX
    )
    callee = _linked_leaf(
        selector=(0xD00DFEED + seed) & 0xFFFFFFFF,
        value=0x21 + (seed % 0x40),
        slot=0x02,
        magic=0xB1 + (seed % 0x4E),
    )
    return [
        (code.hex(), "", f"minproxy#{seed}"),
        (callee, "", f"mincallee#{seed}@0x{target_addr:040x}"),
    ]


def cross_call_pair(seed: int = 0) -> List[Tuple[str, str, str]]:
    """A calls B at a constant (PUSH20) address with ATTACKER-tainted
    calldata (CALLDATACOPY of the full input) and then branches on
    the returned word (MLOAD 0 after the CALL) — the known positive
    for BOTH `tainted-cross-contract-call-arg` (attacker bytes flow
    into the callee's calldata through a `constant`-provenance edge)
    and `untrusted-return-data-in-guard` (the post-call guard's
    condition carries the ATTACKER|UNKNOWN memory-join signature)."""
    b_addr = (0x3C << 152) | ((0xB0B00000 + seed) & 0xFFFFFFFF)
    sel = (0xFEEDC0DE + seed) & 0xFFFFFFFF
    fn_at, fail_at = 17, 64
    code = bytearray([0x60, 0x00, 0x35, 0x60, 0xE0, 0x1C, 0x80, 0x63])
    code += sel.to_bytes(4, "big")
    code += bytes([0x14, 0x60, fn_at, 0x57, 0x00])
    assert len(code) == fn_at
    # calldatacopy(0, 0, cds)
    code += bytes([0x5B, 0x36, 0x60, 0x00, 0x60, 0x00, 0x37])
    # call(GAS, B, 0, 0, cds, 0, 32)
    code += bytes([0x60, 0x20, 0x60, 0x00, 0x36, 0x60, 0x00, 0x60, 0x00])
    code += bytes([0x73]) + b_addr.to_bytes(20, "big")
    code += bytes([0x5A, 0xF1, 0x50])
    # if (mload(0)) INVALID — guard on the callee's return word
    code += bytes([0x60, 0x00, 0x51, 0x60, fail_at, 0x57, 0x00])
    assert len(code) == fail_at
    code += bytes([0x5B, 0xFE])
    callee = _linked_leaf(
        selector=(0x0B5E55ED + seed) & 0xFFFFFFFF,
        value=0x31 + (seed % 0x40),
        slot=0x03,
        magic=0xC2 + (seed % 0x3D),
    )
    return [
        (bytes(code).hex(), "", f"crosscaller#{seed}"),
        (callee, "", f"crosscallee#{seed}@0x{b_addr:040x}"),
    ]


def _linked_leaf(
    selector: int, value: int, slot: int, magic: int
) -> str:
    """The shared callee shape of the link fixtures: one-selector
    dispatcher, `sstore(slot, value)`, then a guarded INVALID
    (SWC-110) so every leaf has a findable issue and a per-variant
    fingerprint axis (`value`/`magic`)."""
    fn_at, fail_at = 17, 33
    code = bytearray([0x60, 0x00, 0x35, 0x60, 0xE0, 0x1C, 0x80, 0x63])
    code += selector.to_bytes(4, "big")
    code += bytes([0x14, 0x60, fn_at, 0x57, 0x00])
    assert len(code) == fn_at
    code += bytes([0x5B, 0x60, value & 0xFF, 0x60, slot & 0xFF, 0x55])
    code += bytes([0x60, 0x04, 0x35, 0x60, magic & 0xFF, 0x14])
    code += bytes([0x60, fail_at, 0x57, 0x00])
    assert len(code) == fail_at
    code += bytes([0x5B, 0xFE])
    return bytes(code).hex()


def poison_contract(seed: int = 0) -> str:
    """The quarantine differential's poison fixture: a syntactically
    ordinary dispatcher (one storage-writing function ending in a
    guarded INVALID, so a normal analysis WOULD report SWC-110) whose
    selectors are distinctive per seed. The contract is behaviorally
    benign — what makes it "poison" in the chaos tests is the harness:
    wave faults are injected while (and only while) this contract is
    resident, modelling a contract whose lowering reliably wedges the
    device. The differential then asserts every OTHER contract's
    issue set is identical with and without the poison in the corpus,
    and the poison itself settles FAILED with
    DegradationReason.QUARANTINED."""
    fn_at = 22
    fail_at = 38
    sel = (0xBADC0FFE + seed * 0x11) & 0xFFFFFFFF
    code = bytearray(
        [0x60, 0x00, 0x35, 0x60, 0xE0, 0x1C, 0x80, 0x63]
    )  # selector = CALLDATALOAD(0) >> 224; DUP1; PUSH4
    code += sel.to_bytes(4, "big")
    code += bytes([0x14, 0x60, fn_at, 0x57])  # EQ; PUSH1 fn; JUMPI
    code += bytes([0x60, 0x00, 0x80, 0xFD])  # no match: revert(0,0)
    while len(code) < fn_at:
        code += bytes([0x00])
    code += bytes([0x5B, 0x60, 0x01 + (seed % 16), 0x60, 0x00, 0x55])
    code += bytes([0x60, 0x04, 0x35])  # CALLDATALOAD(4)
    code += bytes([0x60, 0xC3, 0x14])  # == 0xc3 ?
    code += bytes([0x60, fail_at, 0x57, 0x00])  # JUMPI fail; STOP
    assert len(code) == fail_at
    code += bytes([0x5B, 0xFE])  # fail: JUMPDEST; INVALID (SWC-110)
    return bytes(code).hex()


def synth_bench_corpus(
    n_contracts: int,
    seed: int = 2024,
    loops: int = 4,
    degraders: int = 4,
    wides: int = 6,
    deadweights: int = 2,
    cleans: int = 2,
    dupes: int = 0,
    forks: int = 0,
    proxy_pairs: int = 0,
    minimal_proxies: int = 0,
    cross_call_pairs: int = 0,
    inputs: Optional[Path] = None,
) -> List[Tuple[str, str, str]]:
    """The round-5 benchmark corpus: fixture constant-mutants plus
    hand-assembled deep-loop, cap-degrading, wide-branching, and
    static-deadweight shapes, so the A/B exercises bounded loops,
    device degradation/takeover, the ownership gate, the breadth
    regime (sequential walk exponential vs device branch-coverage
    closure), and the static prune layer in one measured run."""
    rng = random.Random(seed)
    corpus = synth_corpus(
        max(
            0,
            n_contracts
            - loops
            - degraders
            - wides
            - deadweights
            - cleans
            - dupes
            - forks
            - 2 * (proxy_pairs + minimal_proxies + cross_call_pairs),
        ),
        seed=seed,
        inputs=inputs,
    )
    for k in range(loops):
        cap = (0x1F, 0x3F, 0x7F, 0xFF)[k % 4]
        corpus.append((loop_contract(cap), "", f"loop#{k}"))
    for k in range(degraders):
        at = 0x2000 + 0x400 * (k % 4)
        corpus.append((degrader_contract(at), "", f"degrader#{k}"))
    for k in range(wides):
        corpus.append((wide_contract(6 + (k % 3), seed=k), "", f"wide#{k}"))
    for k in range(deadweights):
        corpus.append((deadweight_contract(seed=k), "", f"deadweight#{k}"))
    for k in range(cleans):
        corpus.append((clean_contract(seed=k), "", f"clean#{k}"))
    # the verdict-store population (mythril_tpu/store): `dupes` exact
    # byte-for-byte copies of earlier rows (the exact-hit tier's
    # repeat traffic) and `forks` single-selector-mutated fork pairs
    # (base variant + mutant variant — the incremental tier's
    # fingerprint-diff traffic)
    base_rows = [row for row in corpus if row[0]] or [
        (fork_contract(0, 0), "", "storebase#0")
    ]
    for k in range(dupes):
        src = base_rows[k % len(base_rows)]
        corpus.append((src[0], "", f"{src[2]}#dupe{k}"))
    for k in range(forks):
        corpus.append(
            (fork_contract(seed=k // 2, variant=k % 2), "", f"fork#{k}")
        )
    # the linker's known-positive population: EIP-1967 proxy pairs
    # (every other one with a deliberate storage collision), EIP-1167
    # minimal proxies, and tainted A-calls-B pairs — the bench link
    # leg asserts these resolve
    for k in range(proxy_pairs):
        corpus.extend(proxy_pair(seed=k, variant=0, collide=bool(k % 2)))
    for k in range(minimal_proxies):
        corpus.extend(minimal_proxy(seed=k))
    for k in range(cross_call_pairs):
        corpus.extend(cross_call_pair(seed=k))
    rng.shuffle(corpus)
    return corpus[:n_contracts]


def _check_skeleton(original: bytes, mutant: bytes) -> bool:
    """Same instruction skeleton: identical opcode bytes at identical
    offsets (only PUSH immediates may differ)."""
    if len(original) != len(mutant):
        return False
    starts = _instruction_starts(original)
    return starts == _instruction_starts(mutant) and all(
        original[pc] == mutant[pc] for pc in starts
    )
