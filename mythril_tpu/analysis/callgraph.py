"""Interactive call-graph HTML for `myth analyze --graph`.

Reference parity: mythril/analysis/callgraph.py:220-250 — extracts
vis.js-style node/edge dicts from the statespace and renders an HTML
page (hierarchical LR layout; `--phrack` switches to the monochrome
zine look).
"""

from __future__ import annotations

import re

from jinja2 import Environment, PackageLoader, select_autoescape

from mythril_tpu.laser.ethereum.cfg import NodeFlags
from mythril_tpu.laser.smt import simplify

default_opts = {
    "autoResize": True,
    "height": "100%",
    "width": "100%",
    "manipulation": False,
    "layout": {
        "improvedLayout": True,
        "hierarchical": {
            "enabled": True,
            "levelSeparation": 450,
            "nodeSpacing": 200,
            "treeSpacing": 100,
            "blockShifting": True,
            "edgeMinimization": True,
            "parentCentralization": False,
            "direction": "LR",
            "sortMethod": "directed",
        },
    },
    "nodes": {
        "color": "#000000",
        "borderWidth": 1,
        "borderWidthSelected": 2,
        "chosen": True,
        "shape": "box",
        "font": {"align": "left", "color": "#FFFFFF"},
    },
    "edges": {
        "font": {
            "color": "#FFFFFF",
            "face": "arial",
            "background": "none",
            "strokeWidth": 0,
            "strokeColor": "#ffffff",
            "align": "horizontal",
            "multi": False,
            "vadjust": 0,
        }
    },
    "physics": {"enabled": False},
}

phrack_opts = {
    "nodes": {
        "color": "#000000",
        "borderWidth": 1,
        "borderWidthSelected": 1,
        "shapeProperties": {"borderDashes": False, "borderRadius": 0},
        "chosen": True,
        "shape": "box",
        "font": {"face": "courier new", "align": "left", "color": "#000000"},
    },
    "edges": {
        "font": {
            "color": "#000000",
            "face": "courier new",
            "background": "none",
            "strokeWidth": 0,
            "strokeColor": "#ffffff",
            "align": "horizontal",
            "multi": False,
            "vadjust": 0,
        }
    },
}

default_colors = [
    {
        "border": "#26996f",
        "background": "#2f7e5b",
        "highlight": {"border": "#26996f", "background": "#28a16f"},
    },
    {
        "border": "#9e42b3",
        "background": "#842899",
        "highlight": {"border": "#9e42b3", "background": "#933da6"},
    },
    {
        "border": "#b82323",
        "background": "#991d1d",
        "highlight": {"border": "#b82323", "background": "#a61f1f"},
    },
    {
        "border": "#4753bf",
        "background": "#3b46a1",
        "highlight": {"border": "#4753bf", "background": "#424db3"},
    },
]

phrack_color = {
    "border": "#000000",
    "background": "#ffffff",
    "highlight": {"border": "#000000", "background": "#ffffff"},
}


def extract_nodes(statespace):
    nodes = []
    color_map = {}
    for node_key in statespace.nodes:
        node = statespace.nodes[node_key]
        code_split = []
        for state in node.states:
            try:
                instruction = state.get_current_instruction()
            except IndexError:
                continue
            if instruction["opcode"].startswith("PUSH"):
                code_line = "%d %s %s" % (
                    instruction["address"],
                    instruction["opcode"],
                    instruction["argument"],
                )
            elif (
                instruction["opcode"].startswith("JUMPDEST")
                and NodeFlags.FUNC_ENTRY in node.flags
                and instruction["address"] == node.start_addr
            ):
                code_line = node.function_name
            else:
                code_line = "%d %s" % (instruction["address"], instruction["opcode"])
            code_line = re.sub(
                "([0-9a-f]{8})[0-9a-f]+", lambda m: m.group(1) + "(...)", code_line
            )
            code_split.append(code_line)

        truncated_code = (
            "\n".join(code_split)
            if (len(code_split) < 7)
            else "\n".join(code_split[:6]) + "\n(click to expand +)"
        )

        contract_name = node.get_cfg_dict()["contract_name"]
        if contract_name not in color_map.keys():
            color = default_colors[len(color_map) % len(default_colors)]
            color_map[contract_name] = color

        nodes.append(
            {
                "id": str(node_key),
                "color": color_map.get(contract_name, default_colors[0]),
                "size": 150,
                "fullLabel": "\n".join(code_split),
                "label": truncated_code,
                "truncLabel": truncated_code,
                "isExpanded": False,
            }
        )
    return nodes


def extract_edges(statespace):
    edges = []
    for edge in statespace.edges:
        if edge.condition is None:
            label = ""
        else:
            label = str(simplify(edge.condition)).replace("\n", "")
        label = re.sub(
            r"([^_])([\d]{2}\d+)", lambda m: m.group(1) + hex(int(m.group(2))), label
        )
        edges.append(
            {
                "from": str(edge.as_dict["from"]),
                "to": str(edge.as_dict["to"]),
                "arrows": "to",
                "label": label,
                "smooth": {"type": "cubicBezier"},
            }
        )
    return edges


def generate_graph(
    statespace,
    title="Mythril-TPU / LASER Symbolic VM",
    physics=False,
    phrackify=False,
):
    """Render the callgraph HTML for a finished statespace."""
    env = Environment(
        loader=PackageLoader("mythril_tpu.analysis"),
        autoescape=select_autoescape(["html", "xml"]),
    )
    template = env.get_template("callgraph.html")
    graph_opts = default_opts
    graph_opts["physics"]["enabled"] = physics

    return template.render(
        title=title,
        nodes=extract_nodes(statespace),
        edges=extract_edges(statespace),
        phrackify=phrackify,
        opts=graph_opts,
    )
