"""Interactive call-graph HTML for `myth analyze --graph`.

Covers mythril/analysis/callgraph.py: turns the statespace into
vis.js node/edge dicts and renders the HTML page (hierarchical
left-to-right layout; `--phrack` switches to the monochrome zine
look). The vis.js option trees are assembled from small shared pieces
instead of spelled out literally.
"""

from __future__ import annotations

import re

from jinja2 import Environment, PackageLoader, select_autoescape

from mythril_tpu.laser.ethereum.cfg import NodeFlags
from mythril_tpu.laser.smt import simplify

MAX_PREVIEW_LINES = 6


def _edge_font(color: str, face: str = "arial") -> dict:
    return {
        "color": color,
        "face": face,
        "background": "none",
        "strokeWidth": 0,
        "strokeColor": "#ffffff",
        "align": "horizontal",
        "multi": False,
        "vadjust": 0,
    }


def _node_style(font_color: str, face: str = None) -> dict:
    font = {"align": "left", "color": font_color}
    if face:
        font["face"] = face
    return {
        "color": "#000000",
        "borderWidth": 1,
        "borderWidthSelected": 2,
        "chosen": True,
        "shape": "box",
        "font": font,
    }


default_opts = {
    "autoResize": True,
    "height": "100%",
    "width": "100%",
    "manipulation": False,
    "layout": {
        "improvedLayout": True,
        "hierarchical": {
            "enabled": True,
            "levelSeparation": 450,
            "nodeSpacing": 200,
            "treeSpacing": 100,
            "blockShifting": True,
            "edgeMinimization": True,
            "parentCentralization": False,
            "direction": "LR",
            "sortMethod": "directed",
        },
    },
    "nodes": _node_style("#FFFFFF"),
    "edges": {"font": _edge_font("#FFFFFF")},
    "physics": {"enabled": False},
}

phrack_opts = {
    "nodes": dict(
        _node_style("#000000", face="courier new"),
        borderWidthSelected=1,
        shapeProperties={"borderDashes": False, "borderRadius": 0},
    ),
    "edges": {"font": _edge_font("#000000", face="courier new")},
}


def _shade(border: str, background: str, highlight_bg: str) -> dict:
    return {
        "border": border,
        "background": background,
        "highlight": {"border": border, "background": highlight_bg},
    }


default_colors = [
    _shade("#26996f", "#2f7e5b", "#28a16f"),
    _shade("#9e42b3", "#842899", "#933da6"),
    _shade("#b82323", "#991d1d", "#a61f1f"),
    _shade("#4753bf", "#3b46a1", "#424db3"),
]

phrack_color = _shade("#000000", "#ffffff", "#ffffff")

_ELIDE_HEX = ("([0-9a-f]{8})[0-9a-f]+", lambda m: m.group(1) + "(...)")


def _listing_line(node, state) -> str:
    """One disassembly line for a state, or None past end-of-code."""
    try:
        instr = state.get_current_instruction()
    except IndexError:
        return None
    if instr["opcode"].startswith("PUSH"):
        line = "%d %s %s" % (instr["address"], instr["opcode"], instr["argument"])
    elif (
        instr["opcode"].startswith("JUMPDEST")
        and NodeFlags.FUNC_ENTRY in node.flags
        and instr["address"] == node.start_addr
    ):
        line = node.function_name
    else:
        line = "%d %s" % (instr["address"], instr["opcode"])
    return re.sub(*_ELIDE_HEX, line)


def extract_nodes(statespace):
    nodes = []
    palette = {}
    for node_key, node in statespace.nodes.items():
        listing = [
            line
            for line in (_listing_line(node, s) for s in node.states)
            if line is not None
        ]
        if len(listing) <= MAX_PREVIEW_LINES:
            preview = "\n".join(listing)
        else:
            preview = (
                "\n".join(listing[:MAX_PREVIEW_LINES]) + "\n(click to expand +)"
            )

        who = node.get_cfg_dict()["contract_name"]
        if who not in palette:
            palette[who] = default_colors[len(palette) % len(default_colors)]

        nodes.append(
            {
                "id": str(node_key),
                "color": palette.get(who, default_colors[0]),
                "size": 150,
                "fullLabel": "\n".join(listing),
                "label": preview,
                "truncLabel": preview,
                "isExpanded": False,
            }
        )
    return nodes


def extract_edges(statespace):
    edges = []
    for edge in statespace.edges:
        label = ""
        if edge.condition is not None:
            label = str(simplify(edge.condition)).replace("\n", "")
        label = re.sub(
            r"([^_])([\d]{2}\d+)",
            lambda m: m.group(1) + hex(int(m.group(2))),
            label,
        )
        edges.append(
            {
                "from": str(edge.as_dict["from"]),
                "to": str(edge.as_dict["to"]),
                "arrows": "to",
                "label": label,
                "smooth": {"type": "cubicBezier"},
            }
        )
    return edges


def generate_graph(
    statespace,
    title="Mythril-TPU / LASER Symbolic VM",
    physics=False,
    phrackify=False,
):
    """Render the callgraph HTML for a finished statespace."""
    env = Environment(
        loader=PackageLoader("mythril_tpu.analysis"),
        autoescape=select_autoescape(["html", "xml"]),
    )
    opts = default_opts
    opts["physics"]["enabled"] = physics
    return env.get_template("callgraph.html").render(
        title=title,
        nodes=extract_nodes(statespace),
        edges=extract_edges(statespace),
        phrackify=phrackify,
        opts=opts,
    )
