"""Issue and Report: the user-facing analysis output.

Reference parity: mythril/analysis/report.py:21-321 — `Issue` carries
SWC id, severity, descriptions, gas bounds and the concrete
transaction sequence (source info attached later via `add_code_info`);
`Report` renders text/markdown (jinja2 templates), json, and the SWC
standard jsonv2 format.
"""

from __future__ import annotations

import hashlib
import json
import logging
import operator
from time import time
from typing import Any, Dict, List, Optional

from jinja2 import Environment, PackageLoader

from mythril_tpu.analysis.swc_data import SWC_TO_TITLE
from mythril_tpu.laser.execution_info import ExecutionInfo
from mythril_tpu.support.signatures import SignatureDB
from mythril_tpu.support.source_support import Source
from mythril_tpu.support.start_time import StartTime
from mythril_tpu.support.support_utils import get_code_hash

log = logging.getLogger(__name__)


class Issue:
    """One security finding at one program location."""

    def __init__(
        self,
        contract,
        function_name,
        address,
        swc_id,
        title,
        bytecode,
        gas_used=(None, None),
        severity=None,
        description_head="",
        description_tail="",
        transaction_sequence=None,
    ):
        self.title = title
        self.contract = contract
        self.function = function_name
        self.address = address
        self.description_head = description_head
        self.description_tail = description_tail
        self.description = "%s\n%s" % (description_head, description_tail)
        self.severity = severity
        self.swc_id = swc_id
        self.min_gas_used, self.max_gas_used = gas_used
        self.filename = None
        self.code = None
        self.lineno = None
        self.source_mapping = None
        self.discovery_time = time() - StartTime().global_start_time
        self.bytecode_hash = get_code_hash(bytecode)
        self.transaction_sequence = transaction_sequence

    @property
    def transaction_sequence_users(self):
        return self.transaction_sequence

    @property
    def transaction_sequence_jsonv2(self):
        return (
            self.add_block_data(self.transaction_sequence)
            if self.transaction_sequence
            else None
        )

    @staticmethod
    def add_block_data(transaction_sequence: Dict) -> Dict:
        """Attach plausible block context so jsonv2 test cases replay."""
        for step in transaction_sequence["steps"]:
            step["gasLimit"] = "0x7d000"
            step["gasPrice"] = "0x773594000"
            step["blockCoinbase"] = "0xcbcbcbcbcbcbcbcbcbcbcbcbcbcbcbcbcbcbcbcb"
            step["blockDifficulty"] = "0xa7d7343662e26"
            step["blockGasLimit"] = "0x7d0000"
            step["blockNumber"] = "0x66e393"
            step["blockTime"] = "0x5bfa4639"
        return transaction_sequence

    @property
    def as_dict(self):
        issue = {
            "title": self.title,
            "swc-id": self.swc_id,
            "contract": self.contract,
            "description": self.description,
            "function": self.function,
            "severity": self.severity,
            "address": self.address,
            "tx_sequence": self.transaction_sequence,
            "min_gas_used": self.min_gas_used,
            "max_gas_used": self.max_gas_used,
            "sourceMap": self.source_mapping,
        }
        if self.filename and self.lineno:
            issue["filename"] = self.filename
            issue["lineno"] = self.lineno
        if self.code:
            issue["code"] = self.code
        return issue

    def _set_internal_compiler_error(self):
        self.severity = "Low"
        self.description_tail += (
            " This issue is reported for internal compiler generated code."
        )
        self.description = "%s\n%s" % (self.description_head, self.description_tail)
        self.code = ""

    def add_code_info(self, contract) -> None:
        """Attach file/line/code via the contract's source maps."""
        if self.address and hasattr(contract, "get_source_info"):
            codeinfo = contract.get_source_info(
                self.address, constructor=(self.function == "constructor")
            )
            if codeinfo is None:
                self.source_mapping = self.address
                return
            self.filename = codeinfo.filename
            self.code = codeinfo.code
            self.lineno = codeinfo.lineno
            if self.lineno is None:
                self._set_internal_compiler_error()
            self.source_mapping = codeinfo.solc_mapping
        else:
            self.source_mapping = self.address

    def resolve_function_names(self) -> None:
        """Best-effort function names for each tx step via SignatureDB."""
        if (
            self.transaction_sequence is None
            or "steps" not in self.transaction_sequence
        ):
            return
        signatures = SignatureDB()
        for step in self.transaction_sequence["steps"]:
            _hash = step["input"][:10]
            try:
                sig = signatures.get(_hash)
                step["name"] = sig[0] if len(sig) > 0 else "unknown"
            except ValueError:
                step["name"] = "unknown"


class Report:
    """A renderable collection of issues."""

    environment = Environment(
        loader=PackageLoader("mythril_tpu.analysis"), trim_blocks=True
    )

    def __init__(
        self,
        contracts=None,
        exceptions=None,
        execution_info: Optional[List[ExecutionInfo]] = None,
    ):
        self.issues: Dict[bytes, Issue] = {}
        self.solc_version = ""
        self.meta: Dict[str, Any] = {}
        self.source = Source()
        self.source.get_source_from_contracts_list(contracts)
        self.exceptions = exceptions or []
        self.execution_info = execution_info or []

    def sorted_issues(self):
        issue_list = [issue.as_dict for _, issue in self.issues.items()]
        return sorted(issue_list, key=operator.itemgetter("address", "title"))

    def append_issue(self, issue: Issue) -> None:
        m = hashlib.md5()
        m.update((issue.contract + str(issue.address) + issue.title).encode("utf-8"))
        issue.resolve_function_names()
        self.issues[m.digest()] = issue

    def as_text(self) -> str:
        name = self._file_name()
        template = Report.environment.get_template("report_as_text.jinja2")
        return template.render(filename=name, issues=self.sorted_issues())

    def as_json(self) -> str:
        result = {"success": True, "error": None, "issues": self.sorted_issues()}
        return json.dumps(result, sort_keys=True)

    def _get_exception_data(self) -> dict:
        if not self.exceptions:
            return {}
        logs: List[Dict] = []
        for exception in self.exceptions:
            logs += [{"level": "error", "hidden": True, "msg": exception}]
        return {"logs": logs}

    def as_swc_standard_format(self) -> str:
        """The jsonv2 (SWC standard) output."""
        _issues = []
        for _, issue in self.issues.items():
            idx = self.source.get_source_index(issue.bytecode_hash)
            try:
                title = SWC_TO_TITLE[issue.swc_id]
            except KeyError:
                title = "Unspecified Security Issue"
            extra = {"discoveryTime": int(issue.discovery_time * 10**9)}
            if issue.transaction_sequence_jsonv2:
                extra["testCases"] = [issue.transaction_sequence_jsonv2]
            _issues.append(
                {
                    "swcID": "SWC-" + issue.swc_id,
                    "swcTitle": title,
                    "description": {
                        "head": issue.description_head,
                        "tail": issue.description_tail,
                    },
                    "severity": issue.severity,
                    "locations": [{"sourceMap": "%d:1:%d" % (issue.address, idx)}],
                    "extra": extra,
                }
            )

        meta_data = self.meta
        meta_data.update(self._get_exception_data())
        meta_data["mythril_execution_info"] = {}
        for execution_info in self.execution_info:
            meta_data["mythril_execution_info"].update(execution_info.as_dict())

        result = [
            {
                "issues": _issues,
                "sourceType": self.source.source_type,
                "sourceFormat": self.source.source_format,
                "sourceList": self.source.source_list,
                "meta": meta_data,
            }
        ]
        return json.dumps(result, sort_keys=True)

    def as_markdown(self) -> str:
        filename = self._file_name()
        template = Report.environment.get_template("report_as_markdown.jinja2")
        return template.render(filename=filename, issues=self.sorted_issues())

    def _file_name(self):
        if len(self.issues.values()) > 0:
            return list(self.issues.values())[0].filename
