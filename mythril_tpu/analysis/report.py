"""Issue and Report: the user-facing analysis output.

Covers mythril/analysis/report.py. An `Issue` is one finding at one
program location (source info attached later through the contract's
source maps); a `Report` collects deduplicated issues and renders
them as text/markdown (jinja2 templates under analysis/templates/),
plain json, or the SWC-standard jsonv2 format.
"""

from __future__ import annotations

import hashlib
import json
import logging
from time import time
from typing import Any, Dict, List, Optional

from jinja2 import Environment, PackageLoader

from mythril_tpu.analysis.swc_data import SWC_TO_TITLE
from mythril_tpu.laser.execution_info import ExecutionInfo
from mythril_tpu.support.signatures import SignatureDB
from mythril_tpu.support.source_support import Source
from mythril_tpu.support.start_time import StartTime
from mythril_tpu.support.support_utils import get_code_hash

log = logging.getLogger(__name__)

#: fixed block context attached to jsonv2 test cases so they replay
REPLAY_BLOCK_CONTEXT = {
    "gasLimit": "0x7d000",
    "gasPrice": "0x773594000",
    "blockCoinbase": "0xcbcbcbcbcbcbcbcbcbcbcbcbcbcbcbcbcbcbcbcb",
    "blockDifficulty": "0xa7d7343662e26",
    "blockGasLimit": "0x7d0000",
    "blockNumber": "0x66e393",
    "blockTime": "0x5bfa4639",
}


class Issue:
    """One security finding at one program location."""

    def __init__(
        self,
        contract,
        function_name,
        address,
        swc_id,
        title,
        bytecode,
        gas_used=(None, None),
        severity=None,
        description_head="",
        description_tail="",
        transaction_sequence=None,
    ):
        self.contract = contract
        self.function = function_name
        self.address = address
        self.swc_id = swc_id
        self.title = title
        self.severity = severity
        self.description_head = description_head
        self.description_tail = description_tail
        self.description = f"{description_head}\n{description_tail}"
        self.min_gas_used, self.max_gas_used = gas_used
        self.transaction_sequence = transaction_sequence
        self.bytecode_hash = get_code_hash(bytecode)
        self.discovery_time = time() - StartTime().global_start_time
        #: which engine produced the witness (e.g. "device-prepass");
        #: None for issues found by the host walk
        self.provenance: Optional[str] = None
        # source info, attached later by add_code_info
        self.filename = None
        self.code = None
        self.lineno = None
        self.source_mapping = None

    # -- views ---------------------------------------------------------
    @property
    def transaction_sequence_users(self):
        return self.transaction_sequence

    @property
    def transaction_sequence_jsonv2(self):
        if not self.transaction_sequence:
            return None
        return self.add_block_data(self.transaction_sequence)

    @staticmethod
    def add_block_data(transaction_sequence: Dict) -> Dict:
        """Attach plausible block context so jsonv2 test cases replay."""
        for step in transaction_sequence["steps"]:
            step.update(REPLAY_BLOCK_CONTEXT)
        return transaction_sequence

    @property
    def as_dict(self):
        fields = {
            "address": self.address,
            "contract": self.contract,
            "description": self.description,
            "function": self.function,
            "max_gas_used": self.max_gas_used,
            "min_gas_used": self.min_gas_used,
            "severity": self.severity,
            "sourceMap": self.source_mapping,
            "swc-id": self.swc_id,
            "title": self.title,
            "tx_sequence": self.transaction_sequence,
        }
        if self.filename and self.lineno:
            fields["filename"] = self.filename
            fields["lineno"] = self.lineno
        if self.code:
            fields["code"] = self.code
        if self.provenance:
            fields["provenance"] = self.provenance
        return fields

    # -- enrichment ----------------------------------------------------
    def add_code_info(self, contract) -> None:
        """Attach file/line/code via the contract's source maps."""
        if not (self.address and hasattr(contract, "get_source_info")):
            self.source_mapping = self.address
            return
        info = contract.get_source_info(
            self.address, constructor=(self.function == "constructor")
        )
        if info is None:
            self.source_mapping = self.address
            return
        self.filename = info.filename
        self.code = info.code
        self.lineno = info.lineno
        if self.lineno is None:
            self._mark_compiler_generated()
        self.source_mapping = info.solc_mapping

    def _mark_compiler_generated(self):
        self.severity = "Low"
        self.description_tail += (
            " This issue is reported for internal compiler generated code."
        )
        self.description = f"{self.description_head}\n{self.description_tail}"
        self.code = ""

    def resolve_function_names(self) -> None:
        """Best-effort function names for each tx step via SignatureDB."""
        steps = (self.transaction_sequence or {}).get("steps")
        if steps is None:
            return
        db = SignatureDB()
        for step in steps:
            selector = step["input"][:10]
            try:
                names = db.get(selector)
                step["name"] = names[0] if names else "unknown"
            except ValueError:
                step["name"] = "unknown"


def _jsonv2_issue(issue: Issue, source_index: int) -> dict:
    extra = {"discoveryTime": int(issue.discovery_time * 10**9)}
    replay = issue.transaction_sequence_jsonv2
    if replay:
        extra["testCases"] = [replay]
    if issue.provenance:
        extra["detectedBy"] = issue.provenance
    return {
        "swcID": "SWC-" + issue.swc_id,
        "swcTitle": SWC_TO_TITLE.get(issue.swc_id, "Unspecified Security Issue"),
        "description": {
            "head": issue.description_head,
            "tail": issue.description_tail,
        },
        "severity": issue.severity,
        "locations": [{"sourceMap": "%d:1:%d" % (issue.address, source_index)}],
        "extra": extra,
    }


class Report:
    """A renderable collection of issues."""

    environment = Environment(
        loader=PackageLoader("mythril_tpu.analysis"), trim_blocks=True
    )

    def __init__(
        self,
        contracts=None,
        exceptions=None,
        execution_info: Optional[List[ExecutionInfo]] = None,
    ):
        self.issues: Dict[bytes, Issue] = {}
        self.solc_version = ""
        self.meta: Dict[str, Any] = {}
        self.source = Source()
        self.source.get_source_from_contracts_list(contracts)
        self.exceptions = exceptions or []
        self.execution_info = execution_info or []
        #: resilience outcome (support/resilience.py): `partial` is True
        #: when the run was cut short (deadline / signal) and the issue
        #: list is knowingly incomplete; `degradation` carries the
        #: structured reason counts and per-contract completion status
        #: ({"reasons": {reason: n}, "contracts": [{"contract", ...,
        #: "complete", "device_complete"?, "skipped"?}]}). Both render
        #: into json and jsonv2 ONLY when set, so clean runs' output is
        #: byte-identical to before the supervisor existed.
        self.partial: bool = False
        self.degradation: Dict[str, Any] = {}

    def append_issue(self, issue: Issue) -> None:
        fingerprint = hashlib.md5(
            (issue.contract + str(issue.address) + issue.title).encode("utf-8")
        )
        issue.resolve_function_names()
        self.issues[fingerprint.digest()] = issue

    def sorted_issues(self):
        rows = [issue.as_dict for issue in self.issues.values()]
        return sorted(rows, key=lambda row: (row["address"], row["title"]))

    # -- renderers -----------------------------------------------------
    def _render_template(self, template_name: str) -> str:
        template = Report.environment.get_template(template_name)
        return template.render(
            filename=self._file_name(), issues=self.sorted_issues()
        )

    def as_text(self) -> str:
        return self._render_template("report_as_text.jinja2")

    def as_markdown(self) -> str:
        return self._render_template("report_as_markdown.jinja2")

    def as_json(self) -> str:
        payload = {
            "success": True,
            "error": None,
            "issues": self.sorted_issues(),
        }
        if self.partial:
            payload["partial"] = True
        if self.degradation:
            payload["degradation"] = self.degradation
        return json.dumps(payload, sort_keys=True)

    def as_swc_standard_format(self) -> str:
        """The jsonv2 (SWC standard) output."""
        rendered = [
            _jsonv2_issue(issue, self.source.get_source_index(issue.bytecode_hash))
            for issue in self.issues.values()
        ]

        meta_data = self.meta
        if self.partial:
            meta_data["partial"] = True
        if self.degradation:
            meta_data["degradation"] = self.degradation
        if self.exceptions:
            meta_data["logs"] = [
                {"level": "error", "hidden": True, "msg": why}
                for why in self.exceptions
            ]
        meta_data["mythril_execution_info"] = {}
        for info in self.execution_info:
            meta_data["mythril_execution_info"].update(info.as_dict())

        return json.dumps(
            [
                {
                    "issues": rendered,
                    "sourceType": self.source.source_type,
                    "sourceFormat": self.source.source_format,
                    "sourceList": self.source.source_list,
                    "meta": meta_data,
                }
            ],
            sort_keys=True,
        )

    def _file_name(self):
        for issue in self.issues.values():
            return issue.filename
