"""Hybrid concolic fuzzing: device-scale execution + solver-driven
input generation.

The division of labor is the north-star split (SURVEY.md §7.1): the
batched XLA engine executes whole generations of concrete inputs in
one device pass and journals every JUMPI decision per lane; the host
then picks branch directions no input has taken yet, replays the
journaled path prefix *symbolically* through the LASER instruction
semantics (collecting the path condition), asserts the flipped branch,
and asks the solver for calldata that takes it. Each generation's
witnesses become the next generation's lanes — a SAGE-style whitebox
loop where the expensive part (execution) runs wide on the TPU and the
clever part (constraint flipping) runs narrow on the host.

Scope (v1): single contract, intra-contract paths (replay stops at
CALL/CREATE frames).
"""

from __future__ import annotations

import logging
import random
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from mythril_tpu.disassembler.disassembly import Disassembly
from mythril_tpu.exceptions import UnsatError
from mythril_tpu.laser.batch.run import run as batch_run
from mythril_tpu.laser.batch.state import BRANCH_CAP, Status, make_batch, make_code_table
from mythril_tpu.laser.ethereum.evm_exceptions import VmException
from mythril_tpu.laser.ethereum.instructions import Instruction
from mythril_tpu.laser.ethereum.state.account import Account
from mythril_tpu.laser.ethereum.state.calldata import SymbolicCalldata
from mythril_tpu.laser.ethereum.state.world_state import WorldState
from mythril_tpu.laser.ethereum.time_handler import time_handler
from mythril_tpu.laser.ethereum.transaction.transaction_models import (
    MessageCallTransaction,
    TransactionEndSignal,
    TransactionStartSignal,
    get_next_transaction_id,
)
from mythril_tpu.laser.smt import symbol_factory
from mythril_tpu.laser.batch.explore import (
    DEFAULT_ADDRESS as ADDRESS,
    DEFAULT_CALLER as CALLER,
    TRIGGER_KINDS,
)
from mythril_tpu.support.model import get_model

log = logging.getLogger(__name__)


class _ReplayAbort(Exception):
    """Path replay left the supported scope (calls, script mismatch)."""


def _symbolic_replay(
    disassembly: Disassembly, calldata_len: int, script: List[Tuple[int, bool]]
) -> Optional[List[int]]:
    """Follow `script` = [(jumpi_pc, taken), ...] symbolically, flip the
    LAST entry, and solve for calldata taking the flipped direction.
    Returns concrete calldata bytes or None."""
    world_state = WorldState()
    account = Account(ADDRESS, concrete_storage=True)
    account.code = disassembly
    world_state.put_account(account)
    account.set_balance(10**18)

    tx_id = get_next_transaction_id()
    calldata = SymbolicCalldata(tx_id)
    transaction = MessageCallTransaction(
        world_state=world_state,
        identifier=tx_id,
        gas_price=10,
        gas_limit=8_000_000,
        origin=symbol_factory.BitVecVal(CALLER, 256),
        caller=symbol_factory.BitVecVal(CALLER, 256),
        callee_account=world_state[symbol_factory.BitVecVal(ADDRESS, 256)],
        call_data=calldata,
        call_value=0,
    )
    state = transaction.initial_global_state()
    state.transaction_stack.append((transaction, None))
    state.world_state.constraints.append(
        calldata.calldatasize == calldata_len
    )

    time_handler.start_execution(10)
    script = list(script)
    flip_index = len(script) - 1
    seen_branches = 0
    steps = 0

    while True:
        steps += 1
        if steps > 4096:
            raise _ReplayAbort("step budget")
        try:
            instr = state.get_current_instruction()
        except IndexError:
            raise _ReplayAbort("walked off code before target")
        op = instr["opcode"]
        # peek the condition before evaluate pops it: a concrete value
        # disambiguates single survivors whose jump target is pc + 1
        pre_cond_value = None
        if op == "JUMPI" and len(state.mstate.stack) >= 2:
            pre_cond_value = getattr(state.mstate.stack[-2], "value", None)
        try:
            successors = Instruction(op, None).evaluate(state)
        except TransactionStartSignal:
            raise _ReplayAbort("nested call in path")
        except TransactionEndSignal:
            raise _ReplayAbort("halted before target")
        except VmException as e:
            # e.g. a symbolic jump dest the concrete run resolved fine;
            # skip this flip, keep the fuzzing run alive
            raise _ReplayAbort(f"vm exception in replay: {e}")

        if op == "JUMPI":
            if seen_branches >= len(script):
                raise _ReplayAbort("extra branch past script")
            want_taken = script[seen_branches][1]
            if seen_branches == flip_index:
                want_taken = not want_taken
            # identify successors. jumpi_ appends fall-through first and
            # taken second, so a 2-successor result is unambiguous even
            # when the jump target IS the next instruction (pc + 1); only
            # then fall back to the pc comparison for single survivors.
            if len(successors) == 2:
                fallthrough, taken = successors
            else:
                fallthrough = taken = None
                s = successors[0] if successors else None
                if s is None:
                    pass
                elif s.mstate.pc != state.mstate.pc + 1:
                    taken = s
                elif pre_cond_value is not None:
                    # target == pc + 1 with a concrete condition: jumpi_
                    # kept exactly the branch the condition selects
                    if pre_cond_value != 0:
                        taken = s
                    else:
                        fallthrough = s
                else:
                    # symbolic condition with one survivor at pc + 1 can
                    # only be the fall-through (the taken twin would have
                    # survived too if the target were a JUMPDEST)
                    fallthrough = s
            chosen = taken if want_taken else fallthrough
            if chosen is None:
                # the wanted direction is infeasible (engine pruned it)
                return None
            if seen_branches == flip_index:
                # constraints of `chosen` include the flipped condition
                try:
                    model = get_model(
                        tuple(chosen.world_state.constraints),
                        enforce_execution_time=False,
                        solver_timeout=4000,
                    )
                except UnsatError:
                    return None
                data = calldata.concrete(model)
                return [int(b) for b in data[:calldata_len]] + [0] * max(
                    0, calldata_len - len(data)
                )
            seen_branches += 1
            state = chosen
        else:
            if not successors:
                raise _ReplayAbort("dead end")
            state = successors[0]


class HybridFuzzer:
    """Generation loop: device executes, host flips branches."""

    def __init__(
        self,
        code_hex: str,
        calldata_len: int = 68,
        lanes_per_generation: int = 32,
        max_generations: int = 6,
        flips_per_generation: int = 8,
        seed: int = 1,
    ):
        self.code_hex = code_hex[2:] if code_hex.startswith("0x") else code_hex
        self.code = bytes.fromhex(self.code_hex)
        self.calldata_len = calldata_len
        self.lanes_per_generation = lanes_per_generation
        self.max_generations = max_generations
        self.flips_per_generation = flips_per_generation
        self.rng = random.Random(seed)
        # parsed once: replay and seeding share the same objects
        self.disassembly = Disassembly(self.code_hex)
        self.code_table = make_code_table([self.code])
        self.covered: Set[Tuple[int, bool]] = set()
        self.attempted: Set[Tuple[int, bool]] = set()
        self.corpus: List[bytes] = []
        self.storage_writes: Dict[int, Set[int]] = {}
        # concrete trigger inputs per terminal failure kind: a lane that
        # halts INVALID is a ready-made assert-violation witness
        self.triggers: Dict[str, List[bytes]] = {}

    def _seed_inputs(self) -> List[bytes]:
        inputs = [b"\x00" * self.calldata_len]
        for func_hash in self.disassembly.func_hashes:
            selector = bytes.fromhex(func_hash[2:])
            inputs.append(
                selector
                + bytes(
                    self.rng.randrange(256)
                    for _ in range(self.calldata_len - 4)
                )
            )
        while len(inputs) < self.lanes_per_generation:
            inputs.append(
                bytes(self.rng.randrange(256) for _ in range(self.calldata_len))
            )
        return inputs[: self.lanes_per_generation]

    def _run_generation(self, inputs: List[bytes]) -> List[Dict]:
        table = self.code_table
        batch = make_batch(
            len(inputs), calldata=inputs, caller=CALLER, address=ADDRESS
        )
        out, _ = batch_run(batch, table, max_steps=4096)
        status_arr = np.asarray(out.status)
        br_pc = np.asarray(out.br_pc)
        br_taken = np.asarray(out.br_taken)
        br_cnt = np.asarray(out.br_cnt)
        keys = np.asarray(out.storage_keys)
        vals = np.asarray(out.storage_vals)
        cnts = np.asarray(out.storage_cnt)

        lanes = []
        from mythril_tpu.ops import u256

        for i, data in enumerate(inputs):
            kind = TRIGGER_KINDS.get(int(status_arr[i]))
            if kind is not None:
                bucket = self.triggers.setdefault(kind, [])
                if data not in bucket and len(bucket) < 16:
                    bucket.append(data)
            journal = [
                (int(br_pc[i, j]), bool(br_taken[i, j]))
                for j in range(min(int(br_cnt[i]), BRANCH_CAP))
            ]
            for entry in journal:
                self.covered.add(entry)
            for k in range(int(cnts[i])):
                slot = u256.to_int(keys[i, k])
                self.storage_writes.setdefault(slot, set()).add(
                    u256.to_int(vals[i, k])
                )
            lanes.append({"calldata": data, "journal": journal})
        return lanes

    def run(self) -> Dict:
        inputs = self._seed_inputs()
        generations = 0
        for gen in range(self.max_generations):
            generations += 1
            lanes = self._run_generation(inputs)
            self.corpus.extend(lane["calldata"] for lane in lanes)

            # frontier: first uncovered flipped direction per lane
            new_inputs: List[bytes] = []
            for lane in lanes:
                if len(new_inputs) >= self.flips_per_generation:
                    break
                journal = lane["journal"]
                for i, (pc, taken) in enumerate(journal):
                    target = (pc, not taken)
                    if target in self.covered or target in self.attempted:
                        continue
                    self.attempted.add(target)
                    try:
                        data = _symbolic_replay(
                            self.disassembly, self.calldata_len, journal[: i + 1]
                        )
                    except _ReplayAbort as e:
                        log.debug("replay abort at %s: %s", target, e)
                        continue
                    if data is not None:
                        new_inputs.append(bytes(data))
                        break
            if not new_inputs:
                break
            # pad the next generation with corpus mutations
            while len(new_inputs) < self.lanes_per_generation:
                parent = self.rng.choice(self.corpus)
                mutated = bytearray(parent)
                mutated[self.rng.randrange(len(mutated))] = self.rng.randrange(256)
                new_inputs.append(bytes(mutated))
            inputs = new_inputs[: self.lanes_per_generation]

        return {
            "generations": generations,
            "covered_branches": sorted(self.covered),
            "corpus_size": len(self.corpus),
            "storage_writes": {
                hex(k): sorted(hex(v) for v in vs)
                for k, vs in self.storage_writes.items()
            },
            "triggers": {
                kind: [data.hex() for data in bucket]
                for kind, bucket in self.triggers.items()
            },
        }
