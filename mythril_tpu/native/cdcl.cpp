// CDCL SAT solver — the native constraint back end.
//
// The reference's entire solver layer is z3's C++ engine behind python
// bindings (reference: mythril/laser/smt/solver/solver.py wraps
// z3.Solver). This framework owns the word-level layer in Python/JAX
// and delegates only the final CNF decision problem to this solver:
// a minisat-style CDCL with two-watched literals, 1UIP clause
// learning, VSIDS + phase saving, Luby restarts and activity-based
// clause-database reduction. Exposed as a C ABI for ctypes.
//
// Build: part of libmythril_native.so (see Makefile).

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

typedef int Lit;  // +-(var+1), DIMACS style externally; internal 2*v+sign

struct Clause {
  float activity = 0.0f;
  int lbd = 0;  // literal block distance at learn time (glue metric)
  bool learnt = false;
  bool deleted = false;
  bool keep_mark = false;
  std::vector<int> lits;  // internal encoding
};

inline int mklit(int var, bool neg) { return 2 * var + (neg ? 1 : 0); }
inline int lit_var(int l) { return l >> 1; }
inline bool lit_neg(int l) { return l & 1; }
inline int lit_not(int l) { return l ^ 1; }

// A watch entry carries a "blocker" literal (the other watched literal
// at attach time): if the blocker is already true the clause is
// satisfied and propagate skips the clause memory entirely — most
// watch-list traffic resolves on this one cached int.
struct Watcher {
  Clause* c;
  int blocker;
  int is_bin;  // binary clause: blocker IS the other literal
};

struct Solver {
  int nvars = 0;
  std::vector<Clause*> clauses;          // problem clauses
  std::vector<Clause*> learnts;          // learnt clauses
  std::vector<std::vector<Watcher>> watches;  // watch lists per literal
  std::vector<int8_t> assigns;           // -1 unset, 0 false, 1 true
  std::vector<int8_t> phase;             // saved phase
  std::vector<Clause*> reason;
  std::vector<int> level;
  std::vector<int> trail;
  std::vector<int> trail_lim;
  std::vector<double> act;               // VSIDS activity
  double var_inc = 1.0;
  double cla_inc = 1.0;
  std::vector<int> order;                // lazy heap: simple activity scan
  size_t qhead = 0;
  bool ok = true;
  int64_t conflicts = 0;
  int64_t propagations = 0;

  // binary heap over activity
  std::vector<int> heap;
  std::vector<int> heap_pos;

  ~Solver() {
    for (auto* c : clauses) delete c;
    for (auto* c : learnts) delete c;
  }

  int new_var() {
    int v = nvars++;
    watches.emplace_back();
    watches.emplace_back();
    assigns.push_back(-1);
    phase.push_back(0);
    reason.push_back(nullptr);
    level.push_back(0);
    act.push_back(0.0);
    heap_pos.push_back(-1);
    heap_insert(v);
    return v;
  }

  // ---- heap ----------------------------------------------------------
  bool heap_lt(int a, int b) { return act[a] > act[b]; }
  void heap_up(int i) {
    int v = heap[i];
    while (i > 0) {
      int p = (i - 1) >> 1;
      if (heap_lt(v, heap[p])) {
        heap[i] = heap[p];
        heap_pos[heap[i]] = i;
        i = p;
      } else
        break;
    }
    heap[i] = v;
    heap_pos[v] = i;
  }
  void heap_down(int i) {
    int v = heap[i];
    for (;;) {
      size_t l = 2 * i + 1, r = 2 * i + 2, best = i;
      int bv = v;
      if (l < heap.size() && heap_lt(heap[l], bv)) { best = l; bv = heap[l]; }
      if (r < heap.size() && heap_lt(heap[r], bv)) { best = r; }
      if (best == (size_t)i) break;
      heap[i] = heap[best];
      heap_pos[heap[i]] = i;
      i = (int)best;
    }
    heap[i] = v;
    heap_pos[v] = i;
  }
  void heap_insert(int v) {
    if (heap_pos[v] >= 0) return;
    heap.push_back(v);
    heap_pos[v] = (int)heap.size() - 1;
    heap_up((int)heap.size() - 1);
  }
  int heap_pop() {
    int v = heap[0];
    heap_pos[v] = -1;
    heap[0] = heap.back();
    heap.pop_back();
    if (!heap.empty()) {
      heap_pos[heap[0]] = 0;
      heap_down(0);
    }
    return v;
  }

  void bump_var(int v) {
    act[v] += var_inc;
    if (act[v] > 1e100) {
      for (auto& a : act) a *= 1e-100;
      var_inc *= 1e-100;
    }
    if (heap_pos[v] >= 0) heap_up(heap_pos[v]);
  }

  // ---- assignment ----------------------------------------------------
  int decision_level() { return (int)trail_lim.size(); }

  // incremental solving: assumptions are re-asserted as the first
  // decisions after every restart; learned clauses are implied by the
  // clause database alone, so they stay valid across queries
  std::vector<int> assumptions;

  int8_t value_lit(int l) {
    int8_t a = assigns[lit_var(l)];
    if (a < 0) return -1;
    return lit_neg(l) ? (int8_t)(1 - a) : a;
  }

  bool enqueue(int l, Clause* from) {
    int8_t v = value_lit(l);
    if (v == 0) return false;  // conflict
    if (v == 1) return true;   // already
    int var = lit_var(l);
    assigns[var] = lit_neg(l) ? 0 : 1;
    phase[var] = assigns[var];
    reason[var] = from;
    level[var] = decision_level();
    trail.push_back(l);
    return true;
  }

  Clause* propagate() {
    while (qhead < trail.size()) {
      int p = trail[qhead++];
      propagations++;
      std::vector<Watcher>& ws = watches[lit_not(p)];
      size_t i = 0, j = 0;
      while (i < ws.size()) {
        Watcher w = ws[i++];
        if (value_lit(w.blocker) == 1) {  // satisfied via cached literal
          ws[j++] = w;
          continue;
        }
        if (w.is_bin) {
          // binary fast path: the blocker is the whole rest of the
          // clause — unit-propagate it without touching the watch
          // structure (Tseitin stores are ~2/3 binary clauses).
          // Analyze expects reason->lits[0] to be the propagated
          // literal; normalize before the clause becomes a reason.
          ws[j++] = w;
          if (w.c->lits[0] != w.blocker)
            std::swap(w.c->lits[0], w.c->lits[1]);
          if (!enqueue(w.blocker, w.c)) {
            while (i < ws.size()) ws[j++] = ws[i++];
            ws.resize(j);
            qhead = trail.size();
            return w.c;
          }
          continue;
        }
        // no deleted-clause check needed: reduce_db eagerly detaches a
        // clause from both watch lists before freeing it, so a watcher
        // can never reference a deleted clause
        Clause* c = w.c;
        auto& lits = c->lits;
        // make sure lits[1] is the false literal (not-p)
        if (lits[0] == lit_not(p)) std::swap(lits[0], lits[1]);
        if (value_lit(lits[0]) == 1) {  // satisfied
          ws[j++] = {c, lits[0], 0};
          continue;
        }
        // find new watch
        bool found = false;
        for (size_t k = 2; k < lits.size(); k++) {
          if (value_lit(lits[k]) != 0) {
            std::swap(lits[1], lits[k]);
            watches[lits[1]].push_back({c, lits[0], 0});
            found = true;
            break;
          }
        }
        if (found) continue;
        // unit or conflict
        ws[j++] = {c, lits[0], 0};
        if (!enqueue(lits[0], c)) {
          // conflict: restore remaining watches
          while (i < ws.size()) ws[j++] = ws[i++];
          ws.resize(j);
          qhead = trail.size();
          return c;
        }
      }
      ws.resize(j);
    }
    return nullptr;
  }

  void bump_clause(Clause* c) {
    c->activity += (float)cla_inc;
    if (c->activity > 1e20f) {
      for (auto* l : learnts) l->activity *= 1e-20f;
      cla_inc *= 1e-20;
    }
  }

  // 1UIP conflict analysis. `seen` is persistent and cleared via
  // `to_clear` — a full O(nvars) reset per conflict dominates analysis
  // cost at bit-blasted sizes (hundreds of thousands of vars).
  std::vector<char> seen;
  std::vector<int> to_clear;
  std::vector<int64_t> lbd_stamp;  // level -> conflict counter stamp
  int last_lbd = 0;  // LBD of the most recently analyzed clause
  std::vector<int> analyze_stack;  // DFS stack for lit_redundant

  uint32_t abstract_level(int v) { return 1u << (level[v] & 31); }

  // distinct decision levels among lits, via a stamped level array
  // (one linear pass, no sort). Callers pass distinct stamps so the
  // pre- and post-minimization counts within one conflict don't
  // collide: pre-pass stamps are negative, final stamps positive.
  int count_levels(const std::vector<int>& lits, int64_t stamp) {
    if (lbd_stamp.size() < (size_t)decision_level() + 1)
      lbd_stamp.resize(decision_level() + 1, -1);
    int n = 0;
    for (size_t k = 0; k < lits.size(); k++) {
      int lv = level[lit_var(lits[k])];
      if (lbd_stamp[lv] != stamp) {
        lbd_stamp[lv] = stamp;
        n++;
      }
    }
    return n;
  }

  // Is p implied by the still-seen learnt literals (+ level 0)? DFS
  // over reasons; marks proven-redundant vars seen (kept on success,
  // rolled back past `top` on failure). Terminates because each var
  // is pushed at most once (marked seen when pushed).
  bool lit_redundant(int p0, uint32_t abstract_levels) {
    analyze_stack.clear();
    analyze_stack.push_back(p0);
    size_t top = to_clear.size();
    while (!analyze_stack.empty()) {
      int p = analyze_stack.back();
      analyze_stack.pop_back();
      Clause* r = reason[lit_var(p)];
      for (size_t k = 1; k < r->lits.size(); k++) {
        int q = r->lits[k];
        int v = lit_var(q);
        if (seen[v] || level[v] == 0) continue;
        if (reason[v] == nullptr || !(abstract_level(v) & abstract_levels)) {
          for (size_t j = top; j < to_clear.size(); j++)
            seen[to_clear[j]] = 0;
          to_clear.resize(top);
          return false;
        }
        seen[v] = 1;
        to_clear.push_back(v);
        analyze_stack.push_back(q);
      }
    }
    return true;
  }
  void analyze(Clause* confl, std::vector<int>& out_learnt, int& out_btlevel) {
    out_learnt.clear();
    out_learnt.push_back(0);  // slot for asserting literal
    if ((int)seen.size() < nvars) seen.resize(nvars, 0);
    to_clear.clear();
    int counter = 0;
    int p = -1;
    size_t idx = trail.size();
    do {
      for (size_t k = (p == -1 ? 0 : 1); k < confl->lits.size(); k++) {
        int q = confl->lits[k];
        int v = lit_var(q);
        if (!seen[v] && level[v] > 0) {
          seen[v] = 1;
          to_clear.push_back(v);
          bump_var(v);
          if (level[v] >= decision_level())
            counter++;
          else
            out_learnt.push_back(q);
        }
      }
      if (confl->learnt) bump_clause(confl);
      // next literal on trail
      while (!seen[lit_var(trail[--idx])]) {}
      p = trail[idx];
      confl = reason[lit_var(p)];
      seen[lit_var(p)] = 0;
      counter--;
    } while (counter > 0);
    out_learnt[0] = lit_not(p);

    // deep conflict-clause minimization (MiniSat ccmin): a learnt
    // literal is dropped when every reason-DFS path from it bottoms
    // out in other learnt literals (seen) or level 0 — the abstract
    // level mask prunes branches that reach a decision level the
    // learnt clause does not contain. Shorter learnts propagate more
    // and earlier.
    size_t jj = 1;
    if (count_levels(out_learnt, -conflicts - 2) <= 6) {
      // deep mode pays on low-LBD clauses (glucose's gate: high
      // redundancy, bounded DFS); on scattered ones the reason-DFS
      // cost per conflict outruns the propagation it saves —
      // measured: ungated deep mode took a mul-heavy fixture from
      // 23.8s to 16.2s but pushed a branch-heavy one from
      // convergence back over its budget
      uint32_t abstract_levels = 0;
      for (size_t k = 1; k < out_learnt.size(); k++)
        abstract_levels |= abstract_level(lit_var(out_learnt[k]));
      for (size_t k = 1; k < out_learnt.size(); k++) {
        int v = lit_var(out_learnt[k]);
        if (reason[v] == nullptr ||
            !lit_redundant(out_learnt[k], abstract_levels))
          out_learnt[jj++] = out_learnt[k];
      }
    } else {
      // basic self-subsumption: drop a literal whose whole reason
      // clause is already inside the learnt set
      for (size_t k = 1; k < out_learnt.size(); k++) {
        int v = lit_var(out_learnt[k]);
        Clause* r = reason[v];
        bool redundant = false;
        if (r != nullptr) {
          redundant = true;
          for (size_t m = 1; m < r->lits.size(); m++) {
            int lv = lit_var(r->lits[m]);
            if (!seen[lv] && level[lv] > 0) {
              redundant = false;
              break;
            }
          }
        }
        if (!redundant) out_learnt[jj++] = out_learnt[k];
      }
    }
    out_learnt.resize(jj);
    for (int v : to_clear) seen[v] = 0;

    // literal block distance: distinct decision levels in the learnt
    // clause — glucose's predictor of clause usefulness
    last_lbd = count_levels(out_learnt, conflicts);

    // minimal backtrack level
    out_btlevel = 0;
    for (size_t k = 1; k < out_learnt.size(); k++)
      if (level[lit_var(out_learnt[k])] > out_btlevel)
        out_btlevel = level[lit_var(out_learnt[k])];
    // move a literal of btlevel to position 1 for watching
    if (out_learnt.size() > 1) {
      size_t maxi = 1;
      for (size_t k = 2; k < out_learnt.size(); k++)
        if (level[lit_var(out_learnt[k])] > level[lit_var(out_learnt[maxi])])
          maxi = k;
      std::swap(out_learnt[1], out_learnt[maxi]);
    }
  }

  void cancel_until(int lvl) {
    if (decision_level() <= lvl) return;
    for (int i = (int)trail.size() - 1; i >= trail_lim[lvl]; i--) {
      int v = lit_var(trail[i]);
      assigns[v] = -1;
      reason[v] = nullptr;
      heap_insert(v);
    }
    trail.resize(trail_lim[lvl]);
    trail_lim.resize(lvl);
    qhead = trail.size();
  }

  bool add_clause_internal(std::vector<int> lits, bool learnt) {
    if (!learnt) {
      // simplify: dedupe, tautology check, drop false lits at level 0
      std::vector<int> out;
      for (int l : lits) {
        int8_t v = value_lit(l);
        if (v == 1) return true;  // satisfied at level 0
        if (v == 0 && level[lit_var(l)] == 0) continue;
        bool dup = false, taut = false;
        for (int o : out) {
          if (o == l) dup = true;
          if (o == lit_not(l)) taut = true;
        }
        if (taut) return true;
        if (!dup) out.push_back(l);
      }
      lits = out;
    }
    if (lits.empty()) { ok = false; return false; }
    if (lits.size() == 1) {
      if (!enqueue(lits[0], nullptr)) { ok = false; return false; }
      return propagate() == nullptr ? true : (ok = false);
    }
    Clause* c = new Clause();
    c->lits = lits;
    c->learnt = learnt;
    (learnt ? learnts : clauses).push_back(c);
    int bin = lits.size() == 2 ? 1 : 0;
    watches[lits[0]].push_back({c, lits[1], bin});
    watches[lits[1]].push_back({c, lits[0], bin});
    return true;
  }

  void reduce_db() {
    // glucose-style: drop the half of learnt clauses with the worst
    // (highest) LBD, activity as tie-break; keep glue clauses
    // (lbd <= 2), binaries, and reason clauses
    std::vector<Clause*> sorted = learnts;
    std::sort(sorted.begin(), sorted.end(), [](Clause* a, Clause* b) {
      if (a->lbd != b->lbd) return a->lbd > b->lbd;
      return a->activity < b->activity;
    });
    size_t target = sorted.size() / 2;
    for (int v = 0; v < nvars; v++)
      if (assigns[v] >= 0 && reason[v] && reason[v]->learnt) reason[v]->keep_mark = 1;
    size_t removed = 0;
    for (auto* c : sorted) {
      if (removed >= target) break;
      if (c->lits.size() <= 2 || c->lbd <= 2 || c->keep_mark) {
        c->keep_mark = 0;
        continue;
      }
      c->deleted = true;
      removed++;
    }
    // compact learnt list; detach deleted clauses from their two watch
    // lists and free them — the solver is persistent across a whole
    // analysis run (SolverSession), so deferring frees to cdcl_delete
    // would leak linearly with total conflicts
    std::vector<Clause*> kept;
    for (auto* c : learnts) {
      if (c->deleted) {
        for (int widx = 0; widx < 2; widx++) {
          auto& ws = watches[c->lits[widx]];
          for (size_t k = 0; k < ws.size(); k++) {
            if (ws[k].c == c) {
              ws[k] = ws.back();
              ws.pop_back();
              break;
            }
          }
        }
        delete c;
        continue;
      }
      c->keep_mark = 0;
      kept.push_back(c);
    }
    learnts = kept;
  }

  static int64_t luby(int64_t i) {
    // Luby sequence: 1,1,2,1,1,2,4,1,1,2,1,1,2,4,8,... (0-indexed).
    // Find the smallest complete subsequence (length 2^seq - 1)
    // containing index i, then recurse into its position.
    int64_t size = 1, seq = 0;
    while (size < i + 1) { seq++; size = 2 * size + 1; }
    while (size - 1 != i) {
      size = (size - 1) >> 1;
      seq--;
      i = i % size;
    }
    return 1LL << seq;
  }

  // returns 1 sat, -1 unsat, 0 budget exhausted
  int solve(int64_t conflict_budget) {
    if (!ok) return -1;
    if (propagate() != nullptr) { ok = false; return -1; }
    int64_t restart_num = 0;
    int64_t limit_base = 100;
    std::vector<int> learnt_clause;
    int64_t next_reduce = 4000;
    for (;;) {
      int64_t restart_limit = limit_base * luby(restart_num);
      int64_t confl_this_restart = 0;
      for (;;) {
        Clause* confl = propagate();
        if (confl != nullptr) {
          conflicts++;
          confl_this_restart++;
          if (decision_level() == 0) return -1;  // toplevel conflict: UNSAT
          int btlevel;
          analyze(confl, learnt_clause, btlevel);
          cancel_until(btlevel);
          add_clause_internal(learnt_clause, true);
          if (!ok) return -1;  // unit learnt conflicted at level 0: UNSAT
          if (learnt_clause.size() > 1) {
            // clause watched; assert first literal
            learnts.back()->lbd = last_lbd;
            enqueue(learnt_clause[0], learnts.back());
          }
          var_inc *= 1.0 / 0.95;
          cla_inc *= 1.0 / 0.999;
          if (conflict_budget >= 0 && conflicts >= conflict_budget) return 0;
          if ((int64_t)learnts.size() >= next_reduce) {
            reduce_db();
            next_reduce += 2000;
          }
        } else {
          if (confl_this_restart >= restart_limit) {
            cancel_until(0);
            restart_num++;
            break;
          }
          // assert pending assumptions as decisions
          bool asserted = false;
          while (decision_level() < (int)assumptions.size()) {
            int p = assumptions[decision_level()];
            int av = p >> 1;
            int want = (p & 1) ? 0 : 1;
            if (assigns[av] >= 0) {
              if (assigns[av] != want) return -2;  // unsat under assumptions
              trail_lim.push_back((int)trail.size());  // vacuous level
              continue;
            }
            trail_lim.push_back((int)trail.size());
            enqueue(p, nullptr);
            asserted = true;
            break;
          }
          if (asserted) continue;  // propagate the assumption

          // decide
          int v = -1;
          while (!heap.empty()) {
            int cand = heap_pop();
            if (assigns[cand] < 0) { v = cand; break; }
          }
          if (v < 0) return 1;  // all assigned: SAT
          trail_lim.push_back((int)trail.size());
          enqueue(mklit(v, phase[v] == 0), nullptr);
        }
      }
    }
  }
};

}  // namespace

extern "C" {

void* cdcl_new() { return new Solver(); }

void cdcl_delete(void* s) { delete (Solver*)s; }

int cdcl_new_var(void* s) { return ((Solver*)s)->new_var(); }

// lits: DIMACS style (+-(var+1)), n entries. Returns 0 if formula
// became trivially unsat.
int cdcl_add_clause(void* s, const int* lits, int n) {
  Solver* solver = (Solver*)s;
  if (!solver->ok) return 0;
  std::vector<int> internal(n);
  for (int i = 0; i < n; i++) {
    int l = lits[i];
    int var = std::abs(l) - 1;
    internal[i] = mklit(var, l < 0);
  }
  solver->add_clause_internal(internal, false);
  return solver->ok ? 1 : 0;
}

// 1 = SAT, -1 = UNSAT, 0 = conflict budget exhausted (unknown)
int cdcl_solve(void* s, int64_t conflict_budget) {
  return ((Solver*)s)->solve(conflict_budget);
}

// value of var in the found model (0/1); -1 if unassigned
int cdcl_value(void* s, int var) {
  Solver* solver = (Solver*)s;
  if (var >= solver->nvars) return -1;
  return solver->assigns[var];
}

int64_t cdcl_conflicts(void* s) { return ((Solver*)s)->conflicts; }

// Create variables until the solver has at least n.
void cdcl_ensure_vars(void* s, int n) {
  Solver* solver = (Solver*)s;
  while (solver->nvars < n) solver->new_var();
}

// Bulk clause load: lits is a 0-separated stream of DIMACS literals
// ("a b 0 c d e 0 ..."), n entries total. One call replaces thousands
// of per-clause FFI crossings. Returns 0 if the formula became
// trivially unsat.
int cdcl_add_clauses_flat(void* s, const int* lits, long long n) {
  Solver* solver = (Solver*)s;
  solver->cancel_until(0);  // clause additions must happen at level 0
  std::vector<int> internal;
  internal.reserve(16);
  for (long long i = 0; i < n; i++) {
    int l = lits[i];
    if (l == 0) {
      if (!solver->ok) return 0;
      solver->add_clause_internal(internal, false);
      internal.clear();
      if (!solver->ok) return 0;
    } else {
      int var = std::abs(l) - 1;
      internal.push_back(mklit(var, l < 0));
    }
  }
  return solver->ok ? 1 : 0;
}

// Solve under assumptions (0-terminated not required; n literals).
// Returns 1 SAT, -1 UNSAT (global or under these assumptions),
// 0 budget exhausted. conflict_budget is an absolute conflict count
// (compare against cdcl_conflicts), so chunked callers keep learned
// progress across calls.
int cdcl_solve_assuming(void* s, int64_t conflict_budget, const int* lits,
                        int n) {
  Solver* solver = (Solver*)s;
  if (!solver->ok) return -1;
  solver->cancel_until(0);
  solver->assumptions.clear();
  for (int i = 0; i < n; i++) {
    int l = lits[i];
    solver->assumptions.push_back(mklit(std::abs(l) - 1, l < 0));
  }
  int r = solver->solve(conflict_budget);
  if (r == -2) {
    solver->cancel_until(0);
    return -1;
  }
  if (r != 1) solver->cancel_until(0);
  return r;
}

// Bulk model extraction: out[v] = 1/0 for v in [0, n); unassigned
// variables read as 0 (model completion).
void cdcl_model_bits(void* s, unsigned char* out, int n) {
  Solver* solver = (Solver*)s;
  for (int v = 0; v < n; v++) {
    out[v] = (v < solver->nvars && solver->assigns[v] == 1) ? 1 : 0;
  }
}

}  // extern "C"
