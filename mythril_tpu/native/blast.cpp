// Native gate synthesis: the Tseitin circuit builders behind the
// bit-blaster (adder/multiplier/divider/comparators/shifters), moved
// out of Python per docs/roadmap.md item 0. The Python Blaster walks
// the term DAG and makes ONE call here per term; this side owns the
// variable counter, the gate cache, and the flat 0-separated DIMACS
// clause store the CDCL session loads deltas from (zero-copy: the
// store pointer is exported, see bl_flat_ptr).
//
// CONTRACT: the CNF produced here is bit-for-bit identical to the
// pure-Python PyBlaster (mythril_tpu/laser/smt/solver/bitblast.py) —
// same variable numbering, same clause order, same simplifications.
// Identical CNF means identical CDCL behavior, identical models, and
// byte-identical golden reports; tests/laser/smt/test_native_blast.py
// asserts stream equality over randomized term DAGs. Any change to a
// simplification rule must land in BOTH implementations.
//
// Reference role anchor: z3's internal bit-blaster (the reference
// delegates all of this to z3; mythril/laser/smt/solver/solver.py).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <unordered_map>
#include <vector>

namespace {

constexpr int32_t TRUE_LIT = 1;
constexpr int32_t FALSE_LIT = -1;

static inline uint64_t mix(uint64_t h, uint64_t v) {
    h ^= v + 0x9e3779b97f4a7c15ULL + (h << 6) + (h >> 2);
    return h;
}

struct Key2 {
    int32_t tag, a, b;
    bool operator==(const Key2 &o) const {
        return tag == o.tag && a == o.a && b == o.b;
    }
};
struct Key2Hash {
    size_t operator()(const Key2 &k) const {
        uint64_t h = 1469598103934665603ULL;
        h = mix(h, (uint32_t)k.tag);
        h = mix(h, (uint32_t)k.a);
        h = mix(h, (uint32_t)k.b);
        return (size_t)h;
    }
};
struct Key3 {
    int32_t tag, a, b, c;
    bool operator==(const Key3 &o) const {
        return tag == o.tag && a == o.a && b == o.b && c == o.c;
    }
};
struct Key3Hash {
    size_t operator()(const Key3 &k) const {
        uint64_t h = 1469598103934665603ULL;
        h = mix(h, (uint32_t)k.tag);
        h = mix(h, (uint32_t)k.a);
        h = mix(h, (uint32_t)k.b);
        h = mix(h, (uint32_t)k.c);
        return (size_t)h;
    }
};
struct VecHash {
    size_t operator()(const std::vector<int32_t> &v) const {
        uint64_t h = 1469598103934665603ULL;
        for (int32_t x : v) h = mix(h, (uint32_t)x);
        return (size_t)h;
    }
};

enum { TAG_XOR = 1, TAG_ITE = 2, TAG_MAJ = 3 };

struct Blaster {
    int32_t nvars = 1;  // var 1 = constant TRUE
    std::vector<int32_t> flat;
    std::unordered_map<std::vector<int32_t>, int32_t, VecHash> and_cache;
    std::unordered_map<Key2, int32_t, Key2Hash> xor_cache;
    std::unordered_map<Key3, int32_t, Key3Hash> k3_cache;  // ite + maj
    std::vector<int32_t> scratch;

    Blaster() {
        flat.reserve(1 << 20);
        flat.push_back(TRUE_LIT);
        flat.push_back(0);
    }

    int32_t new_var() { return ++nvars; }

    void emit1(int32_t a) {
        flat.push_back(a);
        flat.push_back(0);
    }
    void emit2(int32_t a, int32_t b) {
        flat.push_back(a);
        flat.push_back(b);
        flat.push_back(0);
    }
    void emit3(int32_t a, int32_t b, int32_t c) {
        flat.push_back(a);
        flat.push_back(b);
        flat.push_back(c);
        flat.push_back(0);
    }

    // Blaster.add: drop clauses containing TRUE, strip FALSE literals.
    void add_clause(const int32_t *lits, int n) {
        size_t start = flat.size();
        for (int i = 0; i < n; i++) {
            int32_t l = lits[i];
            if (l == TRUE_LIT) {
                flat.resize(start);
                return;
            }
            if (l == FALSE_LIT) continue;
            flat.push_back(l);
        }
        flat.push_back(0);
    }

    int32_t g_and(const int32_t *ins, int n) {
        scratch.clear();
        for (int i = 0; i < n; i++) {
            int32_t l = ins[i];
            if (l == FALSE_LIT) return FALSE_LIT;
            if (l == TRUE_LIT) continue;
            scratch.push_back(l);
        }
        if (scratch.empty()) return TRUE_LIT;
        // sorted(set(lits)): signed ascending, deduplicated
        std::sort(scratch.begin(), scratch.end());
        scratch.erase(std::unique(scratch.begin(), scratch.end()),
                      scratch.end());
        if (scratch.size() == 1) return scratch[0];
        for (int32_t l : scratch) {
            if (std::binary_search(scratch.begin(), scratch.end(), -l))
                return FALSE_LIT;
        }
        auto it = and_cache.find(scratch);
        if (it != and_cache.end()) return it->second;
        int32_t o = new_var();
        for (int32_t l : scratch) emit2(-o, l);
        flat.push_back(o);
        for (int32_t l : scratch) flat.push_back(-l);
        flat.push_back(0);
        and_cache.emplace(scratch, o);
        return o;
    }

    int32_t g_and2(int32_t a, int32_t b) {
        int32_t ins[2] = {a, b};
        return g_and(ins, 2);
    }
    int32_t g_and3(int32_t a, int32_t b, int32_t c) {
        int32_t ins[3] = {a, b, c};
        return g_and(ins, 3);
    }

    int32_t g_or(const int32_t *ins, int n) {
        scratch.reserve((size_t)n);
        std::vector<int32_t> neg(n);
        for (int i = 0; i < n; i++) neg[i] = -ins[i];
        return -g_and(neg.data(), n);
    }
    int32_t g_or2(int32_t a, int32_t b) {
        int32_t ins[2] = {-a, -b};
        return -g_and(ins, 2);
    }

    int32_t g_xor(int32_t a, int32_t b) {
        if (a == FALSE_LIT) return b;
        if (b == FALSE_LIT) return a;
        if (a == TRUE_LIT) return -b;
        if (b == TRUE_LIT) return -a;
        if (a == b) return FALSE_LIT;
        if (a == -b) return TRUE_LIT;
        if (std::abs(b) < std::abs(a)) std::swap(a, b);
        Key2 key{TAG_XOR, a, b};
        auto it = xor_cache.find(key);
        if (it != xor_cache.end()) return it->second;
        int32_t o = new_var();
        emit3(-o, a, b);
        emit3(-o, -a, -b);
        emit3(o, -a, b);
        emit3(o, a, -b);
        xor_cache.emplace(key, o);
        return o;
    }

    int32_t g_ite(int32_t c, int32_t a, int32_t b) {
        if (c == TRUE_LIT) return a;
        if (c == FALSE_LIT) return b;
        if (a == b) return a;
        if (a == TRUE_LIT && b == FALSE_LIT) return c;
        if (a == FALSE_LIT && b == TRUE_LIT) return -c;
        if (a == TRUE_LIT) return g_or2(c, b);
        if (a == FALSE_LIT) return g_and2(-c, b);
        if (b == TRUE_LIT) return g_or2(-c, a);
        if (b == FALSE_LIT) return g_and2(c, a);
        Key3 key{TAG_ITE, c, a, b};
        auto it = k3_cache.find(key);
        if (it != k3_cache.end()) return it->second;
        int32_t o = new_var();
        emit3(-o, -c, a);
        emit3(o, -c, -a);
        emit3(-o, c, b);
        emit3(o, c, -b);
        k3_cache.emplace(key, o);
        return o;
    }

    int32_t g_maj(int32_t a, int32_t b, int32_t c) {
        int nt = 0, nf = 0;
        for (int32_t l : {a, b, c}) {
            if (l == TRUE_LIT) nt++;
            else if (l == FALSE_LIT) nf++;
        }
        if (nt + nf >= 2) {
            if (nt >= 2) return TRUE_LIT;
            if (nf >= 2) return FALSE_LIT;
            for (int32_t l : {a, b, c})
                if (l != TRUE_LIT && l != FALSE_LIT) return l;
        }
        if (a == TRUE_LIT) return g_or2(b, c);
        if (a == FALSE_LIT) return g_and2(b, c);
        if (b == TRUE_LIT) return g_or2(a, c);
        if (b == FALSE_LIT) return g_and2(a, c);
        if (c == TRUE_LIT) return g_or2(a, b);
        if (c == FALSE_LIT) return g_and2(a, b);
        int32_t s[3] = {a, b, c};
        std::stable_sort(s, s + 3, [](int32_t x, int32_t y) {
            return std::abs(x) < std::abs(y);
        });
        Key3 key{TAG_MAJ, s[0], s[1], s[2]};
        auto it = k3_cache.find(key);
        if (it != k3_cache.end()) return it->second;
        int32_t o = new_var();
        emit3(-o, a, b);
        emit3(-o, a, c);
        emit3(-o, b, c);
        emit3(o, -a, -b);
        emit3(o, -a, -c);
        emit3(o, -b, -c);
        k3_cache.emplace(key, o);
        return o;
    }

    // ---- word-level builders (mirror bitblast.py exactly) ------------
    // adder: out must hold w lits; returns carry. b must hold >= w lits.
    int32_t adder(const int32_t *a, const int32_t *b, int w, int32_t cin,
                  int32_t *out) {
        int32_t c = cin;
        for (int i = 0; i < w; i++) {
            out[i] = g_xor(g_xor(a[i], b[i]), c);
            c = g_maj(a[i], b[i], c);
        }
        return c;
    }

    void mul(const int32_t *a, int wa, const int32_t *b, int wb,
             int out_w, int32_t *out) {
        std::vector<int32_t> acc((size_t)out_w, FALSE_LIT);
        std::vector<int32_t> row((size_t)out_w);
        std::vector<int32_t> next((size_t)out_w);
        int bi_max = std::min(wb, out_w);
        for (int i = 0; i < bi_max; i++) {
            if (b[i] == FALSE_LIT) continue;
            int aj_max = std::min(wa, out_w - i);
            for (int j = 0; j < i; j++) row[j] = FALSE_LIT;
            for (int j = 0; j < aj_max; j++)
                row[i + j] = g_and2(b[i], a[j]);
            for (int j = i + aj_max; j < out_w; j++) row[j] = FALSE_LIT;
            adder(acc.data(), row.data(), out_w, FALSE_LIT, next.data());
            acc.swap(next);
        }
        std::memcpy(out, acc.data(), sizeof(int32_t) * (size_t)out_w);
    }

    int32_t eq_bits(const int32_t *a, const int32_t *b, int w) {
        std::vector<int32_t> neq((size_t)w);
        for (int i = 0; i < w; i++) neq[i] = -g_xor(a[i], b[i]);
        return g_and(neq.data(), w);
    }

    int32_t ult_bits(const int32_t *a, const int32_t *b, int w) {
        int32_t lt = FALSE_LIT;
        for (int i = 0; i < w; i++) {
            int32_t x = a[i], y = b[i];
            int32_t d = g_xor(x, y);
            int32_t lo = g_and2(-x, y);
            lt = g_ite(d, lo, lt);
        }
        return lt;
    }

    // kind: 0 = shl, 1 = lshr, 2 = ashr
    void shift(const int32_t *a, int w, const int32_t *sh, int shw,
               int kind, int32_t *out) {
        int nstages = 1;
        while ((1 << nstages) < w) nstages++;  // == max(1, (w-1).bit_length())
        if (w <= 1) nstages = 1;
        int32_t fill = (kind == 2) ? a[w - 1] : FALSE_LIT;
        std::vector<int32_t> cur(a, a + w);
        std::vector<int32_t> shifted((size_t)w);
        for (int s = 0; s < nstages; s++) {
            int k = 1 << s;
            int32_t bit = (s < shw) ? sh[s] : FALSE_LIT;
            if (bit == FALSE_LIT) continue;
            for (int i = 0; i < w; i++) {
                if (kind == 0)
                    shifted[i] = (i - k >= 0) ? cur[i - k] : FALSE_LIT;
                else
                    shifted[i] = (i + k < w) ? cur[i + k] : fill;
            }
            for (int i = 0; i < w; i++)
                cur[i] = g_ite(bit, shifted[i], cur[i]);
        }
        int32_t big = FALSE_LIT;
        if (shw > nstages) big = g_or(sh + nstages, shw - nstages);
        if (big != FALSE_LIT) {
            for (int i = 0; i < w; i++) cur[i] = g_ite(big, fill, cur[i]);
        }
        std::memcpy(out, cur.data(), sizeof(int32_t) * (size_t)w);
    }

    // q,r fresh with the division relation (EVM: x/0 = x%0 = 0)
    void divmod(const int32_t *a, const int32_t *b, int w, int32_t *q,
                int32_t *r) {
        for (int i = 0; i < w; i++) q[i] = new_var();
        for (int i = 0; i < w; i++) r[i] = new_var();
        std::vector<int32_t> zeros((size_t)w, FALSE_LIT);
        int32_t b_zero = eq_bits(b, zeros.data(), w);
        int32_t cl[2];
        for (int i = 0; i < w; i++) {
            cl[0] = -b_zero;
            cl[1] = -q[i];
            add_clause(cl, 2);
        }
        for (int i = 0; i < w; i++) {
            cl[0] = -b_zero;
            cl[1] = -r[i];
            add_clause(cl, 2);
        }
        int w2 = 2 * w;
        std::vector<int32_t> q_ext((size_t)w2, FALSE_LIT),
            b_ext((size_t)w2, FALSE_LIT), r_ext((size_t)w2, FALSE_LIT),
            a_ext((size_t)w2, FALSE_LIT);
        std::copy(q, q + w, q_ext.begin());
        std::copy(b, b + w, b_ext.begin());
        std::copy(r, r + w, r_ext.begin());
        std::copy(a, a + w, a_ext.begin());
        std::vector<int32_t> prod((size_t)w2), total((size_t)w2);
        mul(q_ext.data(), w2, b_ext.data(), w2, w2, prod.data());
        adder(prod.data(), r_ext.data(), w2, FALSE_LIT, total.data());
        int32_t rel = eq_bits(total.data(), a_ext.data(), w2);
        int32_t r_lt_b = ult_bits(r, b, w);
        cl[0] = b_zero;
        cl[1] = rel;
        add_clause(cl, 2);
        cl[0] = b_zero;
        cl[1] = r_lt_b;
        add_clause(cl, 2);
    }
};

}  // namespace

extern "C" {

void *bl_new() { return new Blaster(); }
void bl_free(void *h) { delete static_cast<Blaster *>(h); }

int32_t bl_nvars(void *h) { return static_cast<Blaster *>(h)->nvars; }

long long bl_flat_len(void *h) {
    return (long long)static_cast<Blaster *>(h)->flat.size();
}

const int32_t *bl_flat_ptr(void *h) {
    return static_cast<Blaster *>(h)->flat.data();
}

// allocate n consecutive vars; returns the first id
int32_t bl_new_vars(void *h, int32_t n) {
    Blaster *bl = static_cast<Blaster *>(h);
    int32_t first = bl->nvars + 1;
    bl->nvars += n;
    return first;
}

void bl_add_clause(void *h, const int32_t *lits, int32_t n) {
    static_cast<Blaster *>(h)->add_clause(lits, n);
}

int32_t bl_and(void *h, const int32_t *ins, int32_t n) {
    return static_cast<Blaster *>(h)->g_and(ins, n);
}
int32_t bl_or(void *h, const int32_t *ins, int32_t n) {
    return static_cast<Blaster *>(h)->g_or(ins, n);
}
int32_t bl_xor(void *h, int32_t a, int32_t b) {
    return static_cast<Blaster *>(h)->g_xor(a, b);
}
int32_t bl_ite(void *h, int32_t c, int32_t a, int32_t b) {
    return static_cast<Blaster *>(h)->g_ite(c, a, b);
}
int32_t bl_maj(void *h, int32_t a, int32_t b, int32_t c) {
    return static_cast<Blaster *>(h)->g_maj(a, b, c);
}

int32_t bl_adder(void *h, const int32_t *a, const int32_t *b, int32_t w,
                 int32_t cin, int32_t *out) {
    return static_cast<Blaster *>(h)->adder(a, b, w, cin, out);
}

void bl_mul(void *h, const int32_t *a, int32_t wa, const int32_t *b,
            int32_t wb, int32_t out_w, int32_t *out) {
    static_cast<Blaster *>(h)->mul(a, wa, b, wb, out_w, out);
}

int32_t bl_eq(void *h, const int32_t *a, const int32_t *b, int32_t w) {
    return static_cast<Blaster *>(h)->eq_bits(a, b, w);
}
int32_t bl_ult(void *h, const int32_t *a, const int32_t *b, int32_t w) {
    return static_cast<Blaster *>(h)->ult_bits(a, b, w);
}

void bl_shift(void *h, const int32_t *a, int32_t w, const int32_t *sh,
              int32_t shw, int32_t kind, int32_t *out) {
    static_cast<Blaster *>(h)->shift(a, w, sh, shw, kind, out);
}

void bl_divmod(void *h, const int32_t *a, const int32_t *b, int32_t w,
               int32_t *q, int32_t *r) {
    static_cast<Blaster *>(h)->divmod(a, b, w, q, r);
}

void bl_ite_bits(void *h, int32_t c, const int32_t *a, const int32_t *b,
                 int32_t w, int32_t *out) {
    Blaster *bl = static_cast<Blaster *>(h);
    for (int i = 0; i < w; i++) out[i] = bl->g_ite(c, a[i], b[i]);
}

void bl_and_bits(void *h, const int32_t *a, const int32_t *b, int32_t w,
                 int32_t *out) {
    Blaster *bl = static_cast<Blaster *>(h);
    for (int i = 0; i < w; i++) out[i] = bl->g_and2(a[i], b[i]);
}
void bl_or_bits(void *h, const int32_t *a, const int32_t *b, int32_t w,
                int32_t *out) {
    Blaster *bl = static_cast<Blaster *>(h);
    for (int i = 0; i < w; i++) out[i] = bl->g_or2(a[i], b[i]);
}
void bl_xor_bits(void *h, const int32_t *a, const int32_t *b, int32_t w,
                 int32_t *out) {
    Blaster *bl = static_cast<Blaster *>(h);
    for (int i = 0; i < w; i++) out[i] = bl->g_xor(a[i], b[i]);
}

}  // extern "C"
