// Native keccak-256 (EVM variant) for the host fast path.
//
// Replaces the reference's pysha3 C extension dependency
// (reference: mythril/support/support_utils.py:29-41). Exposed over a
// plain C ABI consumed via ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

constexpr uint64_t kRC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808AULL,
    0x8000000080008000ULL, 0x000000000000808BULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008AULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000AULL,
    0x000000008000808BULL, 0x800000000000008BULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800AULL, 0x800000008000000AULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
};

constexpr int kRot[5][5] = {
    {0, 36, 3, 41, 18},
    {1, 44, 10, 45, 2},
    {62, 6, 43, 15, 61},
    {28, 55, 25, 21, 56},
    {27, 20, 39, 8, 14},
};

inline uint64_t rol(uint64_t v, int n) {
  n &= 63;
  return n == 0 ? v : (v << n) | (v >> (64 - n));
}

void keccak_f(uint64_t st[25]) {
  for (int rnd = 0; rnd < 24; ++rnd) {
    uint64_t c[5], d[5], b[25];
    for (int x = 0; x < 5; ++x)
      c[x] = st[x] ^ st[x + 5] ^ st[x + 10] ^ st[x + 15] ^ st[x + 20];
    for (int x = 0; x < 5; ++x)
      d[x] = c[(x + 4) % 5] ^ rol(c[(x + 1) % 5], 1);
    for (int i = 0; i < 25; ++i) st[i] ^= d[i % 5];
    for (int x = 0; x < 5; ++x)
      for (int y = 0; y < 5; ++y)
        b[y + 5 * ((2 * x + 3 * y) % 5)] = rol(st[x + 5 * y], kRot[x][y]);
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x)
        st[x + 5 * y] =
            b[x + 5 * y] ^ ((~b[(x + 1) % 5 + 5 * y]) & b[(x + 2) % 5 + 5 * y]);
    st[0] ^= kRC[rnd];
  }
}

constexpr size_t kRate = 136;

}  // namespace

extern "C" {

void mtpu_keccak256(const char* data, size_t len, char* out32) {
  uint64_t st[25] = {0};
  size_t off = 0;
  // full blocks
  while (len - off >= kRate) {
    for (size_t i = 0; i < kRate / 8; ++i) {
      uint64_t lane;
      std::memcpy(&lane, data + off + 8 * i, 8);
      st[i] ^= lane;  // little-endian host assumed (x86/ARM/TPU hosts)
    }
    keccak_f(st);
    off += kRate;
  }
  // final partial block with multi-rate padding 0x01 ... 0x80
  unsigned char block[kRate] = {0};
  std::memcpy(block, data + off, len - off);
  block[len - off] = 0x01;
  block[kRate - 1] |= 0x80;
  for (size_t i = 0; i < kRate / 8; ++i) {
    uint64_t lane;
    std::memcpy(&lane, block + 8 * i, 8);
    st[i] ^= lane;
  }
  keccak_f(st);
  std::memcpy(out32, st, 32);
}

// Batched variant: n messages of fixed stride, used for bulk selector
// recovery and corpus code hashing.
void mtpu_keccak256_batch(const char* data, size_t stride, size_t len,
                          size_t n, char* out) {
  for (size_t i = 0; i < n; ++i)
    mtpu_keccak256(data + i * stride, len, out + 32 * i);
}

}  // extern "C"
