"""Native runtime components (C++ sources + the built shared library).

This __init__ exists so setuptools' package discovery ships the
directory — the .so and sources ride along as package data
(pyproject.toml [tool.setuptools.package-data]); nothing here is
importable Python.
"""
