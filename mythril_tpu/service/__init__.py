"""Persistent analysis service: `myth serve`.

A long-lived daemon that owns the device for its lifetime and serves
analysis requests over a local HTTP/JSON API, amortizing process
startup, XLA compile, and arena allocation across requests — the
serving counterpart of the one-shot `myth analyze` pipeline
(docs/architecture.md, "The analysis service").

    jobs.py            job model, bounded queue, admission control
    lane_allocator.py  stripe packing over the fixed device arena
    engine.py          warm arena + continuous-batching wave loop +
                       overlapped host-analysis pool
    server.py          HTTP front, drain-on-SIGTERM wiring
    client.py          stdlib client (`myth submit`)
"""

from mythril_tpu.service.engine import (  # noqa: F401
    AnalysisEngine,
    ServiceConfig,
)
from mythril_tpu.service.jobs import (  # noqa: F401
    Job,
    JobQueue,
    JobState,
    QueueRefusal,
)
from mythril_tpu.service.lane_allocator import LaneAllocator  # noqa: F401
