"""Lane-stripe allocation for the persistent analysis arena.

The service's device arena is ONE fixed-shape StateBatch (the shape is
what keeps the jit'd run kernel warm), carved into `stripes` equal
stripes of `lanes_per_stripe` lanes. A job owns one or more stripes
for its device phase and releases them the moment its exploration
finishes — between two waves, not between two corpus runs — which is
what lets the next queued contract join the very next wave
(continuous lane-level batching, the service counterpart of
continuous batching in LLM serving).

Stripes need not be contiguous: every lane carries its own code-table
row id, so the allocator is a plain free-list + occupancy ledger with
no compaction. Pure host-side bookkeeping, no JAX."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class LaneAllocator:
    """Free-list allocator over `stripes` stripes of
    `lanes_per_stripe` lanes each."""

    def __init__(self, stripes: int, lanes_per_stripe: int) -> None:
        if stripes < 1 or lanes_per_stripe < 1:
            raise ValueError(
                f"arena wants >=1 stripe of >=1 lane, got "
                f"{stripes}x{lanes_per_stripe}"
            )
        self.stripes = stripes
        self.lanes_per_stripe = lanes_per_stripe
        self._free: List[int] = list(range(stripes))
        self._owner: Dict[int, str] = {}  # stripe -> job id
        self._lock = threading.Lock()
        # high-water marks for /stats: how coalesced the waves actually
        # ran (the acceptance signal that concurrent jobs share waves)
        self.max_jobs_resident = 0
        self.max_lanes_busy = 0

    @property
    def n_lanes(self) -> int:
        return self.stripes * self.lanes_per_stripe

    def lanes_of(self, stripe: int) -> List[int]:
        base = stripe * self.lanes_per_stripe
        return list(range(base, base + self.lanes_per_stripe))

    def stripes_needed(self, lanes: int) -> int:
        """Smallest stripe count covering a lane request (ceil)."""
        return max(1, -(-int(lanes) // self.lanes_per_stripe))

    def allocate(self, job_id: str, n_stripes: int = 1) -> Optional[List[int]]:
        """Claim `n_stripes` stripes for `job_id`, or None when the
        arena can't fit the request right now (the job stays queued and
        retries at the next wave boundary). All-or-nothing: a partial
        grant would strand a job half-resident across waves."""
        if n_stripes > self.stripes:
            raise ValueError(
                f"job {job_id} wants {n_stripes} stripes; the arena has "
                f"{self.stripes} — resize the arena, not the request"
            )
        with self._lock:
            if len(self._free) < n_stripes:
                return None
            granted = [self._free.pop(0) for _ in range(n_stripes)]
            for stripe in granted:
                self._owner[stripe] = job_id
            jobs = len(set(self._owner.values()))
            self.max_jobs_resident = max(self.max_jobs_resident, jobs)
            self.max_lanes_busy = max(
                self.max_lanes_busy, len(self._owner) * self.lanes_per_stripe
            )
            return granted

    def release(self, stripes: List[int]) -> None:
        with self._lock:
            for stripe in stripes:
                if stripe in self._owner:
                    del self._owner[stripe]
                    self._free.append(stripe)
            self._free.sort()

    def owner_of(self, stripe: int) -> Optional[str]:
        with self._lock:
            return self._owner.get(stripe)

    def occupancy(self) -> Dict:
        """The /stats view: stripe/lane busy counts plus high-water
        marks (max_jobs_resident > 1 is the proof that concurrent
        requests coalesced into shared waves)."""
        with self._lock:
            busy = len(self._owner)
            return {
                "stripes": self.stripes,
                "lanes_per_stripe": self.lanes_per_stripe,
                "lanes": self.n_lanes,
                "stripes_busy": busy,
                "lanes_busy": busy * self.lanes_per_stripe,
                "jobs_resident": len(set(self._owner.values())),
                "max_jobs_resident": self.max_jobs_resident,
                "max_lanes_busy": self.max_lanes_busy,
            }
