"""Lane-stripe allocation for the persistent analysis arena.

The service's device arena is ONE fixed-shape StateBatch (the shape is
what keeps the jit'd run kernel warm), carved into `stripes` equal
stripes of `lanes_per_stripe` lanes. A job owns one or more stripes
for its device phase and releases them the moment its exploration
finishes — between two waves, not between two corpus runs — which is
what lets the next queued contract join the very next wave
(continuous lane-level batching, the service counterpart of
continuous batching in LLM serving).

With `groups > 1` (myth serve --devices N) the stripes split into
contiguous per-device-group blocks: each group dispatches its own
wave over its own block (service/engine.py runs one dispatch/harvest
pair per group), so a job's stripes must all live in ONE group, and
admission stripes jobs over the groups least-loaded-first — the
static half of the mesh balance; the engine's job migration
(_rebalance) is the live half.

Stripes need not be contiguous within a group: every lane carries its
own code-table row id, so the allocator is a plain free-list +
occupancy ledger with no compaction. Pure host-side bookkeeping, no
JAX."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class LaneAllocator:
    """Free-list allocator over `stripes` stripes of
    `lanes_per_stripe` lanes each, optionally split into `groups`
    contiguous device-group blocks."""

    def __init__(
        self, stripes: int, lanes_per_stripe: int, groups: int = 1
    ) -> None:
        if stripes < 1 or lanes_per_stripe < 1:
            raise ValueError(
                f"arena wants >=1 stripe of >=1 lane, got "
                f"{stripes}x{lanes_per_stripe}"
            )
        if groups < 1 or stripes % groups:
            raise ValueError(
                f"{stripes} stripes do not split evenly into "
                f"{groups} device group(s) — size the arena to the mesh"
            )
        self.stripes = stripes
        self.lanes_per_stripe = lanes_per_stripe
        self.groups = groups
        self.stripes_per_group = stripes // groups
        self._free: List[int] = list(range(stripes))
        self._owner: Dict[int, str] = {}  # stripe -> job id
        self._lock = threading.Lock()
        # high-water marks for /stats: how coalesced the waves actually
        # ran (the acceptance signal that concurrent jobs share waves)
        self.max_jobs_resident = 0
        self.max_lanes_busy = 0

    @property
    def n_lanes(self) -> int:
        return self.stripes * self.lanes_per_stripe

    @property
    def lanes_per_group(self) -> int:
        return self.stripes_per_group * self.lanes_per_stripe

    def group_of(self, stripe: int) -> int:
        return stripe // self.stripes_per_group

    def lanes_of(self, stripe: int) -> List[int]:
        base = stripe * self.lanes_per_stripe
        return list(range(base, base + self.lanes_per_stripe))

    def group_lanes(self, group: int) -> List[int]:
        base = group * self.lanes_per_group
        return list(range(base, base + self.lanes_per_group))

    def stripes_needed(self, lanes: int) -> int:
        """Smallest stripe count covering a lane request (ceil)."""
        return max(1, -(-int(lanes) // self.lanes_per_stripe))

    def _free_by_group(self) -> Dict[int, List[int]]:
        out: Dict[int, List[int]] = {g: [] for g in range(self.groups)}
        for stripe in self._free:
            out[self.group_of(stripe)].append(stripe)
        return out

    def allocate(
        self, job_id: str, n_stripes: int = 1, group: Optional[int] = None
    ) -> Optional[List[int]]:
        """Claim `n_stripes` stripes for `job_id`, or None when no
        group can fit the request right now (the job stays queued and
        retries at the next wave boundary). All-or-nothing AND
        single-group: a job striped across groups would need its wave
        split across two dispatch streams. With `group`, the grant is
        pinned (the engine's job migration targets an idle group);
        otherwise the least-loaded group with room wins — admission
        stripes jobs over the device groups."""
        if n_stripes > self.stripes_per_group:
            raise ValueError(
                f"job {job_id} wants {n_stripes} stripes; a device "
                f"group holds {self.stripes_per_group} — resize the "
                f"arena (or drop --devices), not the request"
            )
        with self._lock:
            by_group = self._free_by_group()
            if group is not None:
                candidates = [group]
            else:
                # least busy first (fewest owned stripes), gid breaks
                # ties so the layout is deterministic
                candidates = sorted(
                    range(self.groups),
                    key=lambda g: (
                        self.stripes_per_group - len(by_group[g]),
                        g,
                    ),
                )
            chosen = next(
                (
                    g
                    for g in candidates
                    if len(by_group.get(g, [])) >= n_stripes
                ),
                None,
            )
            if chosen is None:
                return None
            granted = by_group[chosen][:n_stripes]
            for stripe in granted:
                self._free.remove(stripe)
                self._owner[stripe] = job_id
            jobs = len(set(self._owner.values()))
            self.max_jobs_resident = max(self.max_jobs_resident, jobs)
            self.max_lanes_busy = max(
                self.max_lanes_busy, len(self._owner) * self.lanes_per_stripe
            )
            return granted

    def release(self, stripes: List[int]) -> None:
        with self._lock:
            for stripe in stripes:
                if stripe in self._owner:
                    del self._owner[stripe]
                    self._free.append(stripe)
            self._free.sort()

    def owner_of(self, stripe: int) -> Optional[str]:
        with self._lock:
            return self._owner.get(stripe)

    def jobs_in_group(self, group: int) -> List[str]:
        """Distinct job ids resident in `group`, in stripe order."""
        with self._lock:
            seen = []
            for stripe in sorted(self._owner):
                if self.group_of(stripe) == group:
                    job = self._owner[stripe]
                    if job not in seen:
                        seen.append(job)
            return seen

    def occupancy(self) -> Dict:
        """The /stats view: stripe/lane busy counts plus high-water
        marks (max_jobs_resident > 1 is the proof that concurrent
        requests coalesced into shared waves) and the per-group
        occupancy the mesh counters surface."""
        with self._lock:
            busy = len(self._owner)
            per_group = []
            for g in range(self.groups):
                owned = [
                    s for s in self._owner if self.group_of(s) == g
                ]
                per_group.append(
                    {
                        "group": g,
                        "stripes_busy": len(owned),
                        "stripes": self.stripes_per_group,
                        "jobs_resident": len(
                            {self._owner[s] for s in owned}
                        ),
                    }
                )
            return {
                "stripes": self.stripes,
                "lanes_per_stripe": self.lanes_per_stripe,
                "lanes": self.n_lanes,
                "stripes_busy": busy,
                "lanes_busy": busy * self.lanes_per_stripe,
                "jobs_resident": len(set(self._owner.values())),
                "max_jobs_resident": self.max_jobs_resident,
                "max_lanes_busy": self.max_lanes_busy,
                "groups": per_group,
            }
