"""HTTP/JSON front of the persistent analysis service (`myth serve`).

Stdlib-only (http.server): the service targets the same no-egress
container the rest of the toolchain runs in, so no web framework.

Endpoints:
  POST /v1/jobs                submit {"code": "0x..."} -> 202 {job_id}
                               (429 queue full, 503 draining, 400 junk)
  GET  /v1/jobs/<id>           job status (+ report when terminal)
  GET  /v1/jobs/<id>/report    long-poll until terminal (?wait_s=30)
  GET  /healthz                liveness + draining flag
  GET  /stats                  queue depth, lane occupancy, wave rate,
                               warm-cache counters, degradation counts
                               (schema_version-pinned)
  GET  /metrics                the whole metrics registry in Prometheus
                               text exposition format (observe/)
  GET  /trace                  recent flight-recorder spans as JSON
                               (?n=512; ?format=perfetto for a
                               Perfetto-loadable trace document)
  POST /v1/drain               begin the graceful drain (also SIGTERM)

Drain semantics (SIGTERM or /v1/drain): new submissions get 503, the
in-flight wave and in-flight host analyses finish, every other
accepted job is checkpointed to a replayable npz
(laser/batch/checkpoint.py) and reported as `checkpointed` — accepted
work is never dropped. The signal handler chains to whatever handler
was installed before it (support/resilience.py keeps its own handlers
restore-and-chain-safe for exactly this embedding)."""

from __future__ import annotations

import json
import logging
import re
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from mythril_tpu.service.engine import AnalysisEngine, ServiceConfig
from mythril_tpu.service.jobs import Job, QueueRefusal

log = logging.getLogger(__name__)

_JOB_PATH = re.compile(r"^/v1/jobs/([0-9a-f]{12})(/report|/trace)?$")

#: QueueRefusal.reason -> HTTP status
_REFUSAL_STATUS = {"full": 429, "draining": 503}

#: Retry-After hints (seconds) riding every backpressure answer: a
#: full queue clears as soon as the next wave settles jobs (come back
#: quickly); a draining replica is going away (find another one — the
#: fleet front reads exactly this to pace its shed/retry policy)
_RETRY_AFTER = {"full": 1, "draining": 5}


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # the engine rides on the server object (ThreadingHTTPServer
    # instantiates a handler per request)
    @property
    def engine(self) -> AnalysisEngine:
        return self.server.engine  # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # route through logging, quietly
        log.debug("http: " + fmt, *args)

    def _reply(
        self, status: int, payload: Dict,
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for key, value in (headers or {}).items():
            self.send_header(key, value)
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, status: int, body: str, content_type: str) -> None:
        data = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _query(self) -> Tuple[str, Dict[str, str]]:
        path, _, query = self.path.partition("?")
        params = {}
        for pair in query.split("&"):
            if "=" in pair:
                key, _, value = pair.partition("=")
                params[key] = value
        return path, params

    # -- GET -----------------------------------------------------------
    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        path, params = self._query()
        if path == "/healthz":
            # the readiness/liveness split: the payload always carries
            # the full health machine (liveness = the process answered
            # at all); `?ready=1` turns the STATUS CODE into the
            # readiness probe a fleet front / load balancer keys on —
            # 503 while warming, compiling, draining, or redlined,
            # with the enumerated reason in the body
            payload = self.engine.health.healthz_payload()
            payload["draining"] = self.engine.draining
            payload["uptime_s"] = round(
                time.monotonic() - self.engine.started_t, 3
            )
            status = 200
            headers = None
            if params.get("ready") and not payload["ready"]:
                status = 503
                headers = {"Retry-After": str(
                    _RETRY_AFTER["draining"]
                    if payload["draining"]
                    else _RETRY_AFTER["full"]
                )}
            self._reply(status, payload, headers=headers)
            return
        if path == "/v1/frontier/export":
            # the cross-host rebalance handoff: a DRAINING replica's
            # unfinished jobs, each with its live exploration frontier
            # (explore.py export_frontier shape) so a survivor seeded
            # with it CONTINUES this replica's work. Guarded: a healthy
            # replica refuses (its jobs are not up for grabs) unless
            # the caller forces the export (tests, operator tooling).
            if not (self.engine.draining or params.get("force")):
                self._reply(
                    409,
                    {"error": "replica is not draining "
                     "(pass ?force=1 to export anyway)"},
                )
                return
            self._reply(200, self.engine.export_frontiers())
            return
        if path == "/stats":
            self._reply(200, self.engine.stats())
            return
        if path == "/metrics":
            # the whole registry, Prometheus text exposition (0.0.4)
            from mythril_tpu import observe

            self._reply_text(
                200,
                observe.registry().prometheus_text(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
            return
        if path == "/trace":
            from mythril_tpu import observe
            from mythril_tpu.observe.spans import flight_recorder

            try:
                n = min(int(params.get("n", 512)), 8192)
            except ValueError:
                n = 512
            spans = flight_recorder().tail(n)
            if params.get("format") == "perfetto":
                self._reply(200, observe.to_perfetto(spans))
                return
            self._reply(
                200,
                {
                    "schema_version": observe.SCHEMA_VERSION,
                    "recorded": flight_recorder().recorded,
                    "dropped": flight_recorder().dropped,
                    "spans": [span.as_dict() for span in spans],
                },
            )
            return
        match = _JOB_PATH.match(path)
        if match:
            job_id, sub = match.group(1), match.group(2) or ""
            if sub == "/trace":
                # the tier-ladder journey (observe/journey.py): what
                # happened to this job, in order, with timestamps
                from mythril_tpu import observe

                job = self.engine.queue.get(job_id)
                if job is None:
                    self._reply(404, {"error": f"unknown job {job_id}"})
                    return
                doc = observe.assemble_journey(job.journey_id)
                if doc is None:
                    from mythril_tpu.observe import journey as _journey

                    doc = {
                        "schema_version": _journey.SCHEMA_VERSION,
                        "journey_id": job.journey_id,
                        "tiers": [],
                        "tier_dwell_s": {},
                        "events": [],
                        "wall_s": 0.0,
                    }
                doc["state"] = job.state
                self._reply(200, doc)
                return
            if sub == "/report":
                wait_s = min(float(params.get("wait_s", 30.0)), 300.0)
                job = self.engine.queue.wait_terminal(job_id, wait_s)
            else:
                job = self.engine.queue.get(job_id)
            if job is None:
                self._reply(404, {"error": f"unknown job {job_id}"})
                return
            self._reply(200, job.as_dict())
            return
        self._reply(404, {"error": f"no route {path}"})

    # -- POST ----------------------------------------------------------
    def do_POST(self) -> None:  # noqa: N802
        path, _ = self._query()
        if path == "/v1/drain":
            # ack first: the drain blocks until checkpoints are flushed
            self._reply(202, {"draining": True})
            threading.Thread(
                target=self.engine.drain, name="myth-serve-drain",
                daemon=True,
            ).start()
            return
        if path != "/v1/jobs":
            self._reply(404, {"error": f"no route {path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
            job = Job(
                code_hex=body["code"],
                max_waves=body.get("max_waves"),
                deadline_s=body.get("deadline_s"),
                host_walk=body.get("host_walk"),
                lanes=body.get("lanes"),
                idempotency_key=body.get("idempotency_key"),
                frontier=body.get("frontier"),
            )
        except (KeyError, ValueError, TypeError) as why:
            self._reply(400, {"error": f"bad request: {why}"})
            return
        try:
            # submit returns the CANONICAL job: a known idempotency
            # key maps a retried submit back to the existing job (the
            # journal seeds the key index across restarts) instead of
            # double-running it
            canonical = self.engine.submit(job)
        except QueueRefusal as refusal:
            self._reply(
                _REFUSAL_STATUS.get(refusal.reason, 503),
                {"error": str(refusal), "reason": refusal.reason},
                headers={"Retry-After": str(
                    _RETRY_AFTER.get(refusal.reason, 1)
                )},
            )
            return
        payload = {"job_id": canonical.id, "state": canonical.state}
        if canonical.id != job.id:
            payload["deduped"] = True
        self._reply(202, payload)


class AnalysisServer:
    """The embeddable server: engine + HTTP listener + drain wiring.

    `myth serve` runs it until drained; tools/serve_smoke.py and the
    service tests run it in-process (port 0 picks a free port)."""

    def __init__(
        self,
        config: Optional[ServiceConfig] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        start_engine: bool = True,
    ) -> None:
        self.engine = AnalysisEngine(config)
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.engine = self.engine  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self._http_thread: Optional[threading.Thread] = None
        self._start_engine = start_engine
        self._closed = False
        self._sampler_stop = threading.Event()
        self._sampler: Optional[threading.Thread] = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "AnalysisServer":
        if self._start_engine:
            self.engine.start()
        if self._http_thread is None:
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="myth-serve-http",
                daemon=True,
            )
            self._http_thread.start()
        if self._sampler is None:
            # the health/saturation sampler: rolls the SLO engine and
            # the device monitor on a clock so mtpu_health_state and
            # mtpu_device_* stay live without a scrape in the loop
            from mythril_tpu import observe

            def _sample_loop():
                while not self._sampler_stop.wait(
                    self.engine.cfg.health_interval_s
                ):
                    try:
                        self.engine.health.sample()
                        observe.device_monitor().sample()
                    except Exception:  # telemetry never sinks serving
                        log.debug("observe sampler tick failed",
                                  exc_info=True)

            try:  # one synchronous tick: the first scrape sees gauges
                self.engine.health.sample()
                observe.device_monitor().sample()
            except Exception:
                log.debug("initial observe sample failed", exc_info=True)
            self._sampler = threading.Thread(
                target=_sample_loop, name="myth-observe-sampler",
                daemon=True,
            )
            self._sampler.start()
        return self

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT -> graceful drain. Chains to the previously
        installed handler, mirroring the courtesy resilience's
        supervisor extends to us."""
        def _drain_handler(signum, frame, _previous={}):
            log.info("signal %s: draining the analysis service", signum)
            threading.Thread(
                target=self.close, name="myth-serve-drain", daemon=True
            ).start()
            previous = _previous.get(signum)
            if callable(previous) and previous not in (
                signal.default_int_handler,
            ):
                previous(signum, frame)

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev = signal.signal(sig, _drain_handler)
            except (ValueError, OSError):
                continue  # not the main thread / exotic embedding
            if prev is not _drain_handler:
                _drain_handler.__defaults__[0][sig] = prev

    def drained(self, timeout_s: Optional[float] = 300.0) -> bool:
        """Block until the drain completes (None = forever)."""
        return self.engine._drained.wait(timeout_s)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._sampler_stop.set()
        self.engine.drain()
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self) -> "AnalysisServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


def serve_forever(
    config: Optional[ServiceConfig] = None,
    host: str = "127.0.0.1",
    port: int = 7341,
) -> None:
    """The `myth serve` entry: run until a drain (SIGTERM/SIGINT or
    POST /v1/drain) completes."""
    server = AnalysisServer(config, host=host, port=port).start()
    server.install_signal_handlers()
    mesh = server.engine.mesh
    mesh_note = (
        f", {mesh.n_groups} device group(s) over {mesh.n_devices} "
        f"device(s)"
        if mesh is not None
        else ""
    )
    print(
        f"myth serve: listening on {server.url} "
        f"(arena {server.engine.cfg.stripes}x"
        f"{server.engine.cfg.lanes_per_stripe} lanes, "
        f"queue {server.engine.cfg.queue_capacity}{mesh_note})",
        flush=True,
    )
    try:
        server.drained(timeout_s=None)
    except KeyboardInterrupt:
        pass
    server.close()
    print("myth serve: drained, bye", flush=True)
