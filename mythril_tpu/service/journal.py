"""Durable job journal: an append-only, fsync'd write-ahead log of
every service job transition, and the replay that makes `myth serve`
crash-consistent.

The drain path (SIGTERM) already loses nothing — but a SIGKILL, an
OOM kill, or a wedged device that takes the process down mid-wave
silently loses every acknowledged job: the queue and the job registry
are pure memory. This module is the standard WAL fix, the same
at-least-once discipline distributed symbolic executors (Manticore's
distributed exploration, PAPERS.md) and serving stacks rely on:

- every transition is appended as one JSON line to the current
  segment (``wal-NNNNNN.jsonl`` under the journal directory) and
  fsync'd BEFORE the client sees the 202 — an acknowledged job is on
  disk or it was never acknowledged;
- on restart (`myth serve --journal DIR --recover`) the engine
  replays every prior segment: jobs whose last event is terminal are
  adopted as history (their banked verdict re-attached from the
  PR-11 store when available), non-terminal jobs are re-admitted —
  deduping through the verdict store so an already-computed verdict
  settles in microseconds instead of re-running — and jobs that were
  IN FLIGHT at the crash get a crash-implication strike toward the
  poison-job quarantine (engine.py);
- after a successful replay the prior segments are compacted away:
  terminal jobs are re-journaled as one compact ``settled`` line in
  the fresh segment, re-admitted jobs re-journal their own
  ``admitted`` lines, and only then are the old files unlinked.

Event vocabulary (docs/architecture.md has the schema table):

  admitted    full code hex + submit params + idempotency key —
              everything recovery needs to re-run the job
  claimed     job ids popped from the queue into the arena
  dispatched  job ids riding one device wave (one line per wave)
  settled     terminal state + code hash + idempotency key
  drain       the clean-shutdown marker; a journal whose last line is
              anything else records a crash

Torn tail lines (the crash landed mid-append) are tolerated: replay
stops that segment at the first unparseable line and counts it.

A failed append (disk full, injected ``service.journal.write``
fault) NEVER fails admission: the journal degrades to non-durable for
the rest of its life, records `DegradationReason.JOURNAL_DEGRADED`
once, and keeps serving — crash-safety is honestly reported as lost
(`/stats journal.degraded`), not faked.

The instant admission tiers (store-hit / static-answer / quarantine)
settle in microseconds; their single ``settled`` line is written
WITHOUT an fsync (``sync=False``) — the work was already delivered to
the client, and losing the line merely loses post-crash GET history,
never work. Full-path events always fsync.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

#: journal record schema — bump on any key-set change; replay refuses
#: records from a NEWER schema (a rolled-back replica must not
#: misparse a newer writer's log) and tolerates older ones
JOURNAL_SCHEMA_VERSION = 1

EVENT_ADMITTED = "admitted"
EVENT_CLAIMED = "claimed"
EVENT_DISPATCHED = "dispatched"
EVENT_SETTLED = "settled"
EVENT_DRAIN = "drain"

#: job states replay treats as terminal (JobState.TERMINAL mirror —
#: kept local so replay never imports the service stack)
TERMINAL_STATES = ("done", "failed", "checkpointed")

_SEGMENT_RE = re.compile(r"^wal-(\d{6})\.jsonl$")


class JournaledJob:
    """One job's replayed journal state."""

    __slots__ = (
        "job_id", "code_hex", "code_hash", "params", "idempotency_key",
        "state", "inflight", "events",
    )

    def __init__(self, job_id: str) -> None:
        self.job_id = job_id
        self.code_hex: Optional[str] = None
        self.code_hash: Optional[str] = None
        self.params: Dict = {}
        self.idempotency_key: Optional[str] = None
        self.state: Optional[str] = None  # last settled state
        self.inflight = False  # claimed/dispatched after last settle
        self.events: List[str] = []

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL_STATES


class JournalReplay:
    """The parsed content of every prior segment."""

    def __init__(self) -> None:
        self.jobs: "Dict[str, JournaledJob]" = {}
        self.records = 0
        self.torn_lines = 0
        self.clean_shutdown = False
        self.segments: List[str] = []

    def _job(self, job_id: str) -> JournaledJob:
        job = self.jobs.get(job_id)
        if job is None:
            job = JournaledJob(job_id)
            self.jobs[job_id] = job
        return job

    def consume(self, rec: Dict) -> None:
        event = rec.get("event")
        self.records += 1
        self.clean_shutdown = event == EVENT_DRAIN
        if event == EVENT_ADMITTED:
            job = self._job(rec["job_id"])
            job.code_hex = rec.get("code")
            job.code_hash = rec.get("code_hash") or job.code_hash
            job.params = dict(rec.get("params") or {})
            job.idempotency_key = rec.get("key") or job.idempotency_key
            job.events.append(event)
        elif event in (EVENT_CLAIMED, EVENT_DISPATCHED):
            for job_id in rec.get("job_ids") or ():
                job = self._job(job_id)
                job.inflight = True
                job.events.append(event)
        elif event == EVENT_SETTLED:
            job = self._job(rec["job_id"])
            job.state = rec.get("state")
            job.code_hash = rec.get("code_hash") or job.code_hash
            job.idempotency_key = rec.get("key") or job.idempotency_key
            job.inflight = False
            job.events.append(event)

    def nonterminal(self) -> List[JournaledJob]:
        """Jobs that must be re-admitted, in journal order."""
        return [j for j in self.jobs.values() if not j.terminal]

    def crash_implicated(self) -> List[JournaledJob]:
        """Jobs in flight at the crash marker — claimed or dispatched
        with no settle, in a journal that did NOT end with the drain
        marker. These take a quarantine strike: whatever killed the
        process mid-wave, they were on the device when it happened."""
        if self.clean_shutdown:
            return []
        return [
            j for j in self.jobs.values() if j.inflight and not j.terminal
        ]


class JobJournal:
    """The append half: one writer per process, one fresh segment per
    process lifetime."""

    def __init__(self, directory: str, fsync: bool = True) -> None:
        self.dir = os.path.abspath(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.fsync = fsync
        self._mu = threading.Lock()
        self._prior = self._existing_segments()
        serial = 1
        if self._prior:
            serial = (
                int(_SEGMENT_RE.match(
                    os.path.basename(self._prior[-1])
                ).group(1))
                + 1
            )
        self.path = os.path.join(self.dir, f"wal-{serial:06d}.jsonl")
        self._fp = open(self.path, "a")
        # -- /stats counters (registry doubles below) ------------------
        self.appends = 0
        self.bytes_written = 0
        self.errors = 0
        self.degraded = False
        self.wall_s = 0.0  # cumulative append+fsync wall (overhead
        # accounting: the chaos harness gates journal cost per settled
        # job against the warm p50)
        self._closed = False
        try:
            from mythril_tpu.observe.registry import registry

            reg = registry()
            self._c_appends = reg.counter(
                "mtpu_journal_appends_total",
                "job-journal records appended",
            )
            self._c_bytes = reg.counter(
                "mtpu_journal_bytes_total", "job-journal bytes appended"
            )
            self._c_errors = reg.counter(
                "mtpu_journal_errors_total",
                "failed journal appends (the journal degrades to "
                "non-durable; admission never fails)",
            )
            for c in (self._c_appends, self._c_bytes, self._c_errors):
                c.inc(0)
        except Exception:
            self._c_appends = self._c_bytes = self._c_errors = None

    # -- segments ------------------------------------------------------
    def _existing_segments(self) -> List[str]:
        try:
            names = sorted(
                n for n in os.listdir(self.dir) if _SEGMENT_RE.match(n)
            )
        except OSError:
            return []
        return [os.path.join(self.dir, n) for n in names]

    # -- append half ---------------------------------------------------
    def append(self, event: str, sync: Optional[bool] = None, **fields) -> bool:
        """Append one record; True when it is durably (or, with
        sync=False, at least OS-buffered) on disk. A failure degrades
        the journal to non-durable for the rest of its life and
        records JOURNAL_DEGRADED — it never raises into admission."""
        if self.degraded or self._closed:
            return False
        rec = dict(fields)
        rec["schema"] = JOURNAL_SCHEMA_VERSION
        rec["ts"] = time.time()
        rec["event"] = event
        line = json.dumps(rec, sort_keys=True) + "\n"
        t0 = time.perf_counter()
        try:
            with self._mu:
                from mythril_tpu.support.resilience import inject

                inject("service.journal.write")
                self._fp.write(line)
                self._fp.flush()
                if self.fsync and (sync is None or sync):
                    os.fsync(self._fp.fileno())
        except Exception as why:
            self.errors += 1
            if self._c_errors is not None:
                self._c_errors.inc()
            self.degraded = True
            try:
                from mythril_tpu.support.resilience import (
                    DegradationLog,
                    DegradationReason,
                )

                DegradationLog().record(
                    DegradationReason.JOURNAL_DEGRADED,
                    site="service.journal.write",
                    detail=str(why),
                )
            except Exception:
                log.warning("journal degraded to non-durable: %s", why)
            return False
        finally:
            self.wall_s += time.perf_counter() - t0
        self.appends += 1
        self.bytes_written += len(line)
        if self._c_appends is not None:
            self._c_appends.inc()
            self._c_bytes.inc(len(line))
        return True

    def job_admitted(self, job) -> bool:
        """The durable admission record — fsync'd BEFORE the caller
        acknowledges the job."""
        return self.append(
            EVENT_ADMITTED,
            job_id=job.id,
            code=job.code.hex(),
            code_hash=_code_hash(job.code),
            key=getattr(job, "idempotency_key", None),
            params={
                "max_waves": job.max_waves,
                "deadline_s": (
                    job.deadline.budget_s if job.deadline else None
                ),
                "host_walk": job.host_walk,
                "lanes": job.lanes,
            },
        )

    def jobs_claimed(self, job_ids: List[str]) -> bool:
        """Unsynced: claim/dispatch records feed the crash-implication
        HEURISTIC (which jobs were on the device), not the no-loss
        guarantee — that lives entirely in the fsync'd admitted and
        settled records. Losing a buffered claim line to a crash can
        only under-strike, never lose a job."""
        if not job_ids:
            return True
        return self.append(EVENT_CLAIMED, sync=False, job_ids=list(job_ids))

    def wave_dispatched(self, job_ids: List[str]) -> bool:
        if not job_ids:
            return True
        return self.append(
            EVENT_DISPATCHED, sync=False, job_ids=list(job_ids)
        )

    def job_settled(self, job, state: str, sync: bool = True) -> bool:
        return self.append(
            EVENT_SETTLED,
            sync=sync,
            job_id=job.id,
            state=state,
            code_hash=_code_hash(job.code),
            key=getattr(job, "idempotency_key", None),
        )

    def mark_drain(self) -> bool:
        """The clean-shutdown marker (a replay that finds it last
        knows no job was in flight)."""
        return self.append(EVENT_DRAIN)

    def close(self) -> None:
        with self._mu:
            if not self._closed:
                self._closed = True
                try:
                    self._fp.close()
                except OSError:
                    pass

    # -- replay half ---------------------------------------------------
    def replay_prior(self) -> JournalReplay:
        """Parse every segment that predates this writer's own."""
        return replay_segments(self._prior)

    def compact(self) -> int:
        """Unlink the prior segments (call AFTER recovery has
        re-journaled what still matters into the fresh segment).
        Returns the number of files removed."""
        removed = 0
        for path in self._prior:
            try:
                os.unlink(path)
                removed += 1
            except OSError:
                continue
        self._prior = []
        return removed

    def stats(self) -> Dict:
        return {
            "enabled": True,
            "dir": self.dir,
            "segment": os.path.basename(self.path),
            "appends": self.appends,
            "bytes": self.bytes_written,
            "errors": self.errors,
            "degraded": self.degraded,
            "wall_s": round(self.wall_s, 6),
            "fsync": self.fsync,
        }


def replay_segments(paths: List[str]) -> JournalReplay:
    """Parse journal segments in order, tolerating torn tail lines
    (the crash landed mid-append) and refusing newer-schema records."""
    replay = JournalReplay()
    for path in paths:
        replay.segments.append(path)
        try:
            with open(path) as fp:
                lines = fp.read().splitlines()
        except OSError as why:
            log.warning("journal segment %s unreadable: %s", path, why)
            continue
        for line in lines:
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                if not isinstance(rec, dict):
                    raise ValueError("record is not an object")
                if int(rec.get("schema", 1)) > JOURNAL_SCHEMA_VERSION:
                    raise ValueError("record schema newer than reader")
            except ValueError:
                # a torn append: everything after it in THIS segment
                # is suspect; later segments are separate writers
                replay.torn_lines += 1
                log.warning(
                    "journal segment %s: torn record, stopping the "
                    "segment here", path,
                )
                break
            replay.consume(rec)
    return replay


def replay_dir(directory: str) -> JournalReplay:
    """Replay every segment under `directory` (read-only helper for
    tools and tests; the engine goes through JobJournal.replay_prior
    so its own fresh segment is excluded)."""
    directory = os.path.abspath(directory)
    try:
        names = sorted(
            n for n in os.listdir(directory) if _SEGMENT_RE.match(n)
        )
    except OSError:
        return JournalReplay()
    return replay_segments([os.path.join(directory, n) for n in names])


def _code_hash(code: bytes) -> str:
    import hashlib

    return hashlib.sha256(code).hexdigest()
